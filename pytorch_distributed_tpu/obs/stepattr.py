"""Exact per-step wall-time attribution + roofline classifier (ISSUE 20).

The serving engine decomposes every request's TTFT exactly
(obs/reqtrace.py); a *training* step's wall time was still only
observable in fragments — exposed comm from the timeline, data stalls
indirectly as goodput events, host syncs statically via shardlint.  This
module closes the identity for training:

    step_time == device_compute + exposed_comm + host_sync
                 + data_wait + other        (recon err <= 0.5% of p50)

**Runtime side** (``StepAttr``): the trainers (``--step-attr``) time
three host wall windows per step with ``perf_counter`` —

- ``data_wait``  around batch acquisition (``next(iter)`` + the chaos
  ``on_batch`` hook, so injected loader delay lands here by design),
- ``device``     around the jitted step call *plus* an explicit
  ``block_until_ready`` on its outputs (the step's blocking transfer —
  without the block, async dispatch smears step N's device time into
  step N+1's windows),
- ``host_sync``  around the remaining host-side drains (meters update,
  metrics logging — the lazy-flush scalar conversion spikes land here),

and close ``other`` as the residual against the meters' step seconds, so
the identity holds *by construction*; the reconciliation error is
exactly the amount by which the measured windows overshoot the step
(clock skew / out-of-band work), fenced at 0.5% of p50.  The device
window splits into ``compute + exposed_comm`` via an exposure fraction:
measured from a timeline capture when one exists
(``exposure_from_timeline``), estimated from the comm ledger's wire
bytes against the chip link bandwidth otherwise — either way the split
sums back to the device window exactly.

**Offline side**: ``summarize`` folds the stamped ``attr_*`` record
fields into p50/p95 shares + the dominant bottleneck class;
``phase_profile``/``roofline`` label each named_scope phase
compute-bound / hbm-bound / comm-bound / host-bound from the
flops/memory ledgers against ``chip_peak_flops``/``chip_hbm_bw`` and
rank a "what to fix first" table; ``write_attr``/``load_attr`` carry the
measured profile into ``autoplan --attr-from`` (plan/cost.py scores with
the *measured* overlap instead of its assumed constant).

Pure stdlib — loaded by file path from the jax-free
``scripts/obs_roofline.py`` CLI.
"""

from __future__ import annotations

import json
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Sequence

# Attribution component order: the identity, in render order.
COMPONENTS = ("compute", "exposed_comm", "host_sync", "data_wait", "other")

# Per-record fields stamped into the metrics JSONL by ``StepAttr.fields``
# (prefixed so the exporter's gauge promotion can pattern on them).
ATTR_FIELDS = (
    "attr_compute_ms", "attr_exposed_comm_ms", "attr_host_sync_ms",
    "attr_data_wait_ms", "attr_other_ms", "attr_device_ms",
    "attr_comm_ms", "attr_recon_err_ms", "data_wait_share",
)

# Assumed backward-overlap fraction for the ledger-estimate exposure
# split — plan/cost.py's DEFAULT_OVERLAP, restated here so this module
# stays import-free for the jax-free CLI.  A timeline capture replaces
# the assumption with a measurement (``exposure_from_timeline``).
ASSUMED_OVERLAP = 0.6

_EMA_ALPHA = 0.1


def _percentile(sorted_vals: Sequence[float], q: float) -> float:
    """Nearest-rank percentile (obs/metrics.py semantics)."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[i]


# ------------------------------------------------------------------ runtime

class StepAttr:
    """Per-step wall-window recorder for the trainer hot loops.

    Usage (both trainers, behind ``--step-attr``)::

        sa = StepAttr(link_bytes_per_s=chip_link_bytes())
        ...
        with sa.data_wait():
            batch = next(batch_iter)
            chaos.on_batch(...)
        with sa.device():
            state, metrics = step(state, batch)
            jax.block_until_ready(metrics)
        with sa.host_sync():
            dt = meters.update(metrics, n)
        extra.update(sa.fields(dt))      # closes the identity, resets

    Windows accumulate within a step (a retried ``next()`` adds to the
    same ``data_wait``); ``fields`` consumes them.  Overhead is six
    ``perf_counter`` calls + one small dict per step (<2% of step p50,
    fenced in RESULTS_stepattr.json via the flightrec A/B methodology).
    """

    def __init__(self, comm_bytes_per_step: float = 0.0,
                 link_bytes_per_s: Optional[float] = None,
                 assumed_overlap: float = ASSUMED_OVERLAP):
        self.comm_bytes_per_step = float(comm_bytes_per_step)
        self.link_bytes_per_s = link_bytes_per_s
        self.assumed_overlap = float(assumed_overlap)
        # timeline-measured exposure overrides the ledger estimate
        self._exposed_frac: Optional[float] = None
        self._comm_frac: Optional[float] = None
        self.exposure_source = "ledger"
        self._t_data = self._t_device = self._t_sync = 0.0
        self.data_wait_ema_ms: Optional[float] = None

    # -- wiring ----------------------------------------------------------
    def set_comm_bytes(self, nbytes: float) -> None:
        """Per-step wire bytes from the comm ledger (set once the lazily
        emitted ledgers exist — earlier steps fall back to comm=0)."""
        self.comm_bytes_per_step = float(nbytes)

    def set_exposure(self, exposed_frac: float,
                     comm_frac: Optional[float] = None,
                     source: str = "timeline") -> None:
        """Measured split: ``exposed_frac`` of the device window is
        exposed comm (``comm_frac`` of it is collective time at all) —
        from ``exposure_from_timeline`` on a profiler capture."""
        self._exposed_frac = min(1.0, max(0.0, float(exposed_frac)))
        if comm_frac is not None:
            self._comm_frac = min(1.0, max(0.0, float(comm_frac)))
        self.exposure_source = source

    # -- the three windows ----------------------------------------------
    @contextmanager
    def data_wait(self):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._t_data += time.perf_counter() - t0

    @contextmanager
    def device(self):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._t_device += time.perf_counter() - t0

    @contextmanager
    def host_sync(self):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._t_sync += time.perf_counter() - t0

    def restart(self) -> None:
        """Drop half-collected windows (eval/checkpoint boundaries —
        the meters' ``restart_clock`` twin)."""
        self._t_data = self._t_device = self._t_sync = 0.0

    # -- closing the identity -------------------------------------------
    def _split_device(self, device_ms: float) -> tuple:
        """(compute_ms, exposed_ms, comm_ms): exact within the window."""
        if device_ms <= 0.0:
            return 0.0, 0.0, 0.0
        if self._exposed_frac is not None:
            exposed = self._exposed_frac * device_ms
            comm = ((self._comm_frac * device_ms)
                    if self._comm_frac is not None else exposed)
        else:
            bw = self.link_bytes_per_s or 0.0
            est = (1e3 * self.comm_bytes_per_step / bw) if bw > 0 else 0.0
            comm = min(device_ms, est)
            exposed = min(device_ms, (1.0 - self.assumed_overlap) * est)
        comm = max(comm, exposed)
        return device_ms - exposed, exposed, comm

    def fields(self, step_time_s: float) -> Dict[str, float]:
        """Close the identity against the meters' step seconds and reset
        the windows.  ``other`` is the residual (logging, heartbeats, lr
        math — host work outside the three windows), clamped at zero;
        ``attr_recon_err_ms`` is the clamp amount, i.e. the exact error
        of ``sum(components) == step_time``."""
        total_ms = max(0.0, step_time_s * 1e3)
        data_ms = self._t_data * 1e3
        device_ms = self._t_device * 1e3
        sync_ms = self._t_sync * 1e3
        self._t_data = self._t_device = self._t_sync = 0.0

        compute_ms, exposed_ms, comm_ms = self._split_device(device_ms)
        residual = total_ms - (device_ms + sync_ms + data_ms)
        other_ms = max(0.0, residual)
        recon_err = max(0.0, -residual)

        if self.data_wait_ema_ms is None:
            self.data_wait_ema_ms = data_ms
        else:
            self.data_wait_ema_ms += _EMA_ALPHA * (
                data_ms - self.data_wait_ema_ms)

        return {
            "attr_compute_ms": round(compute_ms, 4),
            "attr_exposed_comm_ms": round(exposed_ms, 4),
            "attr_host_sync_ms": round(sync_ms, 4),
            "attr_data_wait_ms": round(data_ms, 4),
            "attr_other_ms": round(other_ms, 4),
            "attr_device_ms": round(device_ms, 4),
            "attr_comm_ms": round(comm_ms, 4),
            "attr_recon_err_ms": round(recon_err, 4),
            "data_wait_share": round(
                100.0 * data_ms / total_ms if total_ms > 0 else 0.0, 3),
        }


def exposure_from_timeline(step_stats: Sequence[Any]) -> Optional[Dict[str, float]]:
    """Fold ``obs.timeline.analyze_steps`` records into the measured
    device-window split: mean exposed/window and comm/window fractions
    (feed to ``StepAttr.set_exposure``).  None with no device streams."""
    stats = [s for s in step_stats if getattr(s, "window_ns", 0) > 0]
    if not stats:
        return None
    exposed = sum(s.exposed_ns / s.window_ns for s in stats) / len(stats)
    comm = sum(s.comm_ns / s.window_ns for s in stats) / len(stats)
    return {"exposed_frac": min(1.0, exposed), "comm_frac": min(1.0, comm)}


# ------------------------------------------------------------------ offline

def step_records(records: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """The stamped step records (``--step-attr`` runs) out of a metrics
    JSONL record stream."""
    return [r for r in records
            if r.get("kind", "step") == "step" and "attr_compute_ms" in r]


def phase_event_fields(profile: Dict[str, Any]) -> Dict[str, Any]:
    """``phase_profile`` output → ft_event payload: the phases list rides
    as a JSON string because ``MetricsLogger.flush`` coerces non-primitive
    values with ``float()`` (``phase_event`` decodes it back)."""
    out = dict(profile)
    out["phases"] = json.dumps(out.get("phases", []))
    return out


def phase_event(records: Sequence[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """The last ``stepattr_phases`` ft_event (the trainer books one per
    run once the ledgers exist), or None."""
    evs = [r for r in records if r.get("ft_event") == "stepattr_phases"]
    if not evs:
        return None
    ev = dict(evs[-1])
    if isinstance(ev.get("phases"), str):
        ev["phases"] = json.loads(ev["phases"])
    return ev


def summarize(records: Sequence[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Aggregate the per-step attribution into the report/profile form:
    component p50s, shares of step p50, p95 tails for the two diff-fenced
    series, the dominant bottleneck class, the measured overlap fraction,
    and the identity reconciliation (max err, and as % of step p50)."""
    recs = step_records(records)
    if not recs:
        return None

    def col(key):
        return sorted(float(r.get(key, 0.0)) for r in recs)

    step_ms = col("step_time")
    step_ms = [v * 1e3 for v in step_ms]
    step_p50 = _percentile(step_ms, 0.5)
    comp_p50 = {c: _percentile(col(f"attr_{c}_ms"), 0.5)
                for c in COMPONENTS}
    denom = max(step_p50, 1e-9)
    shares = {c: 100.0 * v / denom for c, v in comp_p50.items()}
    recon = [float(r.get("attr_recon_err_ms", 0.0)) for r in recs]
    comm_p50 = _percentile(col("attr_comm_ms"), 0.5)
    overlap = (1.0 - comp_p50["exposed_comm"] / comm_p50
               if comm_p50 > 0 else None)
    dws = col("data_wait_share")
    hs = col("attr_host_sync_ms")
    return {
        "steps": len(recs),
        "step_ms_p50": step_p50,
        "components_ms_p50": comp_p50,
        "shares_pct": shares,
        "dominant": max(shares, key=lambda c: shares[c]),
        "data_wait_share_p50": _percentile(dws, 0.5),
        "data_wait_share_p95": _percentile(dws, 0.95),
        "host_sync_ms_p50": _percentile(hs, 0.5),
        "host_sync_ms_p95": _percentile(hs, 0.95),
        "recon_err_ms_max": max(recon) if recon else 0.0,
        "recon_err_pct_p50": (100.0 * max(recon) / denom) if recon else 0.0,
        "overlap_measured": overlap,
        "exposure_source": recs[-1].get("attr_exposure_source", "ledger"),
    }


# ------------------------------------------------------------------ roofline

def split_step_bytes(total_bytes: float, params: float) -> Dict[str, float]:
    """Decompose a ``StepCost.bytes`` figure (6·4·params state traffic +
    activation traffic) into per-phase HBM bytes, conserving the total:
    forward reads params and writes activations, backward re-reads both
    and writes grads, the optimizer update streams param/momentum/grad
    state with no activation traffic."""
    p4 = 4.0 * float(params)
    act = max(0.0, float(total_bytes) - 24.0 * float(params))
    return {"forward": p4 + act / 2.0,
            "backward": 2.0 * p4 + act / 2.0,
            "update": 3.0 * p4}


def phase_profile(flops_by_phase: Dict[str, float],
                  bytes_by_phase: Dict[str, float],
                  comm_bytes: float = 0.0,
                  peak_flops: float = 0.0,
                  hbm_bw: float = 0.0,
                  link_bw: float = 0.0,
                  n_devices: int = 1) -> Dict[str, Any]:
    """The per-run static phase ledger the trainer books once as a
    ``stepattr_phases`` ft_event: per named_scope phase algorithmic FLOPs
    (StepCost.breakdown) + HBM bytes, the wire bytes of the collective
    phase, and the chip peaks — everything the jax-free roofline needs,
    embedded so the CLI never touches hardware tables."""
    phases = []
    for name, fl in flops_by_phase.items():
        if fl <= 0.0:
            continue
        phases.append({"name": name, "flops": float(fl),
                       "hbm_bytes": float(bytes_by_phase.get(name, 0.0)),
                       "comm_bytes": 0.0})
    if comm_bytes > 0.0:
        phases.append({"name": "grad_sync", "flops": 0.0,
                       "hbm_bytes": 0.0, "comm_bytes": float(comm_bytes)})
    return {"phases": phases, "peak_flops": float(peak_flops),
            "hbm_bw": float(hbm_bw), "link_bw": float(link_bw),
            "n_devices": int(n_devices)}


def roofline(summary: Dict[str, Any], profile: Dict[str, Any],
             top_k: int = 5) -> Dict[str, Any]:
    """Label every phase and rank the fix-first table.

    Compute phases split the measured ``compute`` p50 by FLOPs share and
    are labeled **compute-bound** when their operational intensity
    (flops/byte) clears the chip ridge point (peak_flops / hbm_bw),
    **hbm-bound** below it; the collective phase carries the measured
    ``exposed_comm`` p50 → **comm-bound**; host_sync/data_wait/other →
    **host-bound**.  Ranking is by headroom: time × (1 − achieved/peak),
    i.e. the milliseconds a perfectly-utilized phase would give back.
    """
    comp = summary["components_ms_p50"]
    peak = max(profile.get("peak_flops", 0.0), 1e-9)
    bw = max(profile.get("hbm_bw", 0.0), 1e-9)
    link = max(profile.get("link_bw", 0.0), 1e-9)
    n_dev = max(int(profile.get("n_devices", 1)), 1)
    ridge = peak / bw

    flop_phases = [p for p in profile.get("phases", [])
                   if p.get("flops", 0.0) > 0.0]
    total_flops = sum(p["flops"] for p in flop_phases) or 1.0
    rows: List[Dict[str, Any]] = []
    for p in flop_phases:
        ms = comp["compute"] * p["flops"] / total_flops
        secs = max(ms / 1e3, 1e-12)
        ach_fl = p["flops"] / n_dev / secs
        ach_bw = p.get("hbm_bytes", 0.0) / n_dev / secs
        intensity = (p["flops"] / p["hbm_bytes"]
                     if p.get("hbm_bytes", 0.0) > 0 else float("inf"))
        label = "compute-bound" if intensity >= ridge else "hbm-bound"
        util = (ach_fl / peak) if label == "compute-bound" else (ach_bw / bw)
        util = min(1.0, util)
        rows.append({"phase": p["name"], "ms": ms, "label": label,
                     "flops_util_pct": min(100.0, 100.0 * ach_fl / peak),
                     "hbm_util_pct": min(100.0, 100.0 * ach_bw / bw),
                     "headroom_ms": ms * (1.0 - util)})
    for p in profile.get("phases", []):
        if p.get("comm_bytes", 0.0) <= 0.0:
            continue
        ms = comp["exposed_comm"]
        secs = max(ms / 1e3, 1e-12)
        util = min(1.0, p["comm_bytes"] / n_dev / secs / link)
        rows.append({"phase": p["name"], "ms": ms, "label": "comm-bound",
                     "link_util_pct": 100.0 * util,
                     "headroom_ms": ms * (1.0 - util)})
    for name, ms in (("host_sync", comp["host_sync"]),
                     ("data_wait", comp["data_wait"]),
                     ("other", comp["other"])):
        rows.append({"phase": name, "ms": ms, "label": "host-bound",
                     "headroom_ms": ms})
    fix_first = sorted(rows, key=lambda r: -r["headroom_ms"])[:top_k]
    return {"ridge_flops_per_byte": ridge, "phases": rows,
            "fix_first": fix_first}


# ------------------------------------------------------- the measured profile

def attr_profile(summary: Dict[str, Any],
                 source: str = "") -> Dict[str, Any]:
    """The planner-facing profile: measured overlap + bottleneck shares
    (autoplan ``--attr-from`` swaps these in for plan/cost.py's assumed
    constants; the plan payload records ``attr_source``)."""
    return {
        "kind": "stepattr_profile",
        "attr_source": source,
        "steps": summary["steps"],
        "step_ms_p50": summary["step_ms_p50"],
        "overlap": summary["overlap_measured"],
        "bottleneck": summary["dominant"],
        "shares_pct": summary["shares_pct"],
        "data_wait_share_p95": summary["data_wait_share_p95"],
        "host_sync_ms_p95": summary["host_sync_ms_p95"],
        "recon_err_pct_p50": summary["recon_err_pct_p50"],
    }


def write_attr(path: str, summary: Dict[str, Any]) -> Dict[str, Any]:
    prof = attr_profile(summary, source=path)
    with open(path, "w") as f:
        json.dump(prof, f, indent=2, sort_keys=True)
        f.write("\n")
    return prof


def load_attr(path: str) -> Dict[str, Any]:
    with open(path) as f:
        prof = json.load(f)
    if prof.get("kind") != "stepattr_profile":
        raise ValueError(
            f"{path} is not a stepattr profile (write one with "
            "scripts/obs_roofline.py --attr-out)")
    prof.setdefault("attr_source", path)
    return prof


# ------------------------------------------------------------------ perfetto

def chrome_counter_events(records: Sequence[Dict[str, Any]],
                          pid: int = 0) -> List[Dict[str, Any]]:
    """Per-component Perfetto counter tracks ("ph": "C") over the run's
    step clock — the attribution read against wall time.  Step records
    are laid end-to-end on their own step_time axis (the JSONL carries
    durations, not absolute stamps)."""
    events: List[Dict[str, Any]] = [{
        "ph": "M", "pid": pid, "name": "process_name",
        "args": {"name": "step attribution"},
    }]
    t_us = 0.0
    for r in step_records(records):
        for c in COMPONENTS:
            events.append({
                "ph": "C", "pid": pid, "ts": t_us,
                "name": f"attr · {c}_ms",
                "args": {"value": float(r.get(f"attr_{c}_ms", 0.0))},
            })
        events.append({
            "ph": "C", "pid": pid, "ts": t_us, "name": "data_wait_share",
            "args": {"value": float(r.get("data_wait_share", 0.0))},
        })
        t_us += max(float(r.get("step_time", 0.0)), 1e-6) * 1e6
    return events


def format_summary_line(summary: Dict[str, Any]) -> str:
    s = summary["shares_pct"]
    parts = " / ".join(f"{c} {s[c]:.1f}%" for c in COMPONENTS)
    return (f"step p50 {summary['step_ms_p50']:.1f}ms = {parts}  "
            f"(dominant: {summary['dominant']})")
