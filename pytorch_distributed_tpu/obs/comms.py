"""Static communication ledger: per-collective bytes/fan-out attribution.

The shardlint baseline (analysis/baseline.json) pins per-kind collective
*totals*; this module keeps the itemized receipt.  From one compiled
step's post-optimization HLO it extracts every collective instruction —
kind, per-device payload bytes, estimated wire bytes, replica-group
fan-out, and the jax scope path it lowered under (``metadata={op_name}``,
fed by ``trace.scope`` / ``named_scope`` annotations like ``grad_sync``
or ``pp_hop``) — so a regression report can say *which* collective grew
and *whose* code emitted it, not just that totals moved.

Ledgers serialize to ``comm_ledger.json`` (one entry per step builder)
and fold into the metrics JSONL as ``model_comm_bytes`` /
``collective_count`` fields; scripts/obs_timeline.py marries them to
measured XPlane collective spans to turn bytes into bus bandwidth.

Wire-byte convention (per participating device, EQuARX-style accounting,
arxiv 2506.17615): for a ``b``-byte per-device payload in a group of
``n`` devices —

- all-reduce:          ``2*(n-1)/n * b``   (ring reduce-scatter+all-gather)
- all-gather:          ``(n-1)/n * b``     (``b`` = gathered result size)
- reduce-scatter:      ``(n-1) * b``       (``b`` = scattered shard size)
- all-to-all:          ``(n-1)/n * b``
- collective-permute:  ``b``               (one hop sends the buffer once)
- collective-broadcast:``b``

Like the rest of ``analysis/hlo.py`` this is pure text parsing — no jax
import — so ledgers can be built (and unit-tested) from HLO fixtures.
"""

from __future__ import annotations

import dataclasses
import json
import re
from typing import Any, Dict, List, Optional, Sequence

from pytorch_distributed_tpu.analysis import hlo as hlo_mod

# Multiplier on per-device payload bytes -> wire bytes, as (numerator-of-
# (n-1) term, divide-by-n).  See module docstring for the derivations.
_WIRE_FACTORS = {
    "all-reduce": (2.0, True),
    "all-gather": (1.0, True),
    "reduce-scatter": (1.0, False),
    "all-to-all": (1.0, True),
}


def wire_bytes(kind: str, payload_bytes: int, group_size: int) -> float:
    """Estimated bytes each participant puts on the wire for one op."""
    n = max(1, int(group_size))
    factor = _WIRE_FACTORS.get(kind)
    if factor is None:  # permute / broadcast: the buffer crosses once
        return float(payload_bytes) if n > 1 else 0.0
    num, div = factor
    if n == 1:
        return 0.0
    return num * (n - 1) * payload_bytes / (n if div else 1)


def wire_encoding_of(shapes) -> str:
    """Wire encoding label for one collective, from its HLO result shapes.

    The narrowest payload dtype wins: quantized-collective ops
    (ops/qcomm.py) ship an int8/fp8 payload with small f32 block-scale
    side-cars, so the f32 scales must not relabel the op.  Plain f32
    collectives — and anything unrecognized — report ``"f32"``."""
    dtypes = {dt for dt, _ in shapes}
    if any(dt.startswith("f8") for dt in dtypes):
        return "fp8"
    if "s8" in dtypes or "u8" in dtypes:
        return "int8"
    if "bf16" in dtypes:
        return "bf16"
    if "f16" in dtypes:
        return "f16"
    return "f32"


def phase_of_op_name(op_name: str) -> str:
    """Coarse step phase of a jax scope path.

    ``transpose(jvp(...))`` components mark autodiff-transposed (backward)
    ops; an ``optimizer``/``grad_sync``/``grad_clip`` scope marks the
    update; everything else under ``jvp`` or the plain forward trace is
    ``forward``.  Pipeline-schedule scopes (``pp_hop``, ``pp*_fwd`` …)
    win over the autodiff classification: a hop is a hop whichever
    direction lowered it."""
    if not op_name:
        return "unknown"
    parts = op_name.split("/")
    for p in parts:
        if p in ("pp_hop", "pp_stage_fwd", "pp1f1b_fwd", "pp1f1b_bwd",
                 "pp1f1b_head", "ppint_fwd", "ppint_bwd", "ppint_head"):
            return p
    for p in parts:
        if p in ("optimizer", "grad_sync", "grad_clip"):
            return p
    if any(p.startswith("transpose(") for p in parts):
        return "backward"
    return "forward"


# parallel/overlap.py wraps each gradient bucket's collective in a
# ``b<k>`` scope (``ag_b<k>`` for the ZeRO delta all-gather buckets), so
# the compiled op_name carries the bucket index through metadata.
_BUCKET_SCOPE = re.compile(r"^(?:ag_)?b(\d+)$")


def bucket_of_op_name(op_name: str) -> int:
    """Bucket index of a collective lowered by the bucketed overlap
    scheduler, or -1 for unbucketed (monolithic) collectives.

    Looks for a ``b<k>`` / ``ag_b<k>`` scope component in the jax scope
    path — always nested under ``grad_sync``/``optimizer``, so per-phase
    attribution still sums: bucketing relabels entries within a phase,
    it never moves bytes across phases."""
    if not op_name:
        return -1
    for p in op_name.split("/"):
        m = _BUCKET_SCOPE.match(p)
        if m:
            return int(m.group(1))
    return -1


@dataclasses.dataclass
class CommEntry:
    """One collective in the ledger (the attributed receipt line)."""

    name: str
    kind: str
    bytes: int            # per-device payload (matches baseline accounting)
    wire_bytes: float     # estimated per-participant wire traffic
    n_groups: int
    group_size: int
    phase: str            # coarse scope phase (phase_of_op_name)
    op_name: str          # full jax scope path
    source: str           # "file:line"
    # Payload dtype label (wire_encoding_of); defaults keep pre-existing
    # comm_ledger.json files loadable (load_ledgers does CommEntry(**e)).
    wire_encoding: str = "f32"
    # Overlap-scheduler bucket index (bucket_of_op_name); -1 = monolithic.
    bucket: int = -1

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class CommLedger:
    """Everything the comm ledger knows about one compiled step."""

    step: str
    mesh_shape: Dict[str, int] = dataclasses.field(default_factory=dict)
    entries: List[CommEntry] = dataclasses.field(default_factory=list)
    # Compiled per-device peak bytes (temp + argument + output from
    # memory_analysis()); 0.0 = unknown (old ledgers, HLO-text fixtures).
    peak_hbm_bytes: float = 0.0

    @property
    def total_bytes(self) -> int:
        return sum(e.bytes for e in self.entries)

    @property
    def total_wire_bytes(self) -> float:
        return sum(e.wire_bytes for e in self.entries)

    @property
    def count(self) -> int:
        return len(self.entries)

    def by_kind(self) -> Dict[str, Dict[str, float]]:
        out: Dict[str, Dict[str, float]] = {}
        for e in self.entries:
            slot = out.setdefault(
                e.kind, {"count": 0, "bytes": 0, "wire_bytes": 0.0})
            slot["count"] += 1
            slot["bytes"] += e.bytes
            slot["wire_bytes"] += e.wire_bytes
        return out

    def by_phase(self) -> Dict[str, Dict[str, float]]:
        out: Dict[str, Dict[str, float]] = {}
        for e in self.entries:
            slot = out.setdefault(
                e.phase, {"count": 0, "bytes": 0, "wire_bytes": 0.0})
            slot["count"] += 1
            slot["bytes"] += e.bytes
            slot["wire_bytes"] += e.wire_bytes
        return out

    def phase_wire_encodings(self, phase: str) -> Dict[str, float]:
        """Per-encoding payload bytes within one phase — obs_report labels
        the grad_sync row by compression mode from this (``{"int8": ...,
        "f32": ...}`` for the quantized decomposition's payload + scales)."""
        out: Dict[str, float] = {}
        for e in self.entries:
            if e.phase == phase:
                out[e.wire_encoding] = out.get(e.wire_encoding, 0.0) + e.bytes
        return out

    def metrics_fields(self) -> Dict[str, float]:
        """The per-step fields the trainers stamp into the metrics JSONL."""
        fields = {
            "model_comm_bytes": float(self.total_bytes),
            "comm_wire_bytes": float(self.total_wire_bytes),
            "collective_count": float(self.count),
        }
        if self.peak_hbm_bytes:
            fields["peak_hbm_bytes"] = float(self.peak_hbm_bytes)
        return fields

    def to_dict(self) -> Dict[str, Any]:
        return {
            "step": self.step,
            "mesh_shape": dict(self.mesh_shape),
            "total_bytes": self.total_bytes,
            "total_wire_bytes": self.total_wire_bytes,
            "count": self.count,
            "peak_hbm_bytes": self.peak_hbm_bytes,
            "by_kind": self.by_kind(),
            "by_phase": self.by_phase(),
            "entries": [e.to_dict() for e in self.entries],
        }


def ledger_from_hlo_text(
    hlo_text: str,
    step: str = "step",
    mesh_shape: Optional[Dict[str, int]] = None,
) -> CommLedger:
    """Build the ledger for one compiled module's text."""
    entries = []
    for d in hlo_mod.collect_collective_details(hlo_text):
        entries.append(CommEntry(
            name=d.name, kind=d.kind, bytes=d.bytes,
            wire_bytes=wire_bytes(d.kind, d.bytes, d.group_size),
            n_groups=d.n_groups, group_size=d.group_size,
            phase=phase_of_op_name(d.op_name), op_name=d.op_name,
            source=d.source, wire_encoding=wire_encoding_of(d.shapes),
            bucket=bucket_of_op_name(d.op_name)))
    return CommLedger(step=step, mesh_shape=dict(mesh_shape or {}),
                      entries=entries)


def compiled_peak_bytes(compiled) -> float:
    """Per-device compiled peak bytes (temp + argument + output) from a
    ``Compiled.memory_analysis()`` — 0.0 when the backend exposes none.
    The same accounting experiments/fused_ce_memory.py and zero_memory.py
    A/B against; surfaced per step in ``obs_report --diff``."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return 0.0
    if ma is None:
        return 0.0
    total = 0.0
    for field in ("temp_size_in_bytes", "argument_size_in_bytes",
                  "output_size_in_bytes"):
        total += float(getattr(ma, field, 0) or 0)
    return total


def ledger_from_jitted(jitted, args: Sequence[Any], *, step: str = "step",
                       mesh=None) -> CommLedger:
    """Lower + compile a jitted step and build its ledger.  NOTE: in jax
    0.4.x the AOT ``.lower().compile()`` path does NOT share the jit call
    cache, so calling this on a step the trainer also executes costs one
    extra compile — the trainers gate it behind an opt-in flag."""
    compiled = jitted.lower(*args).compile()
    text = compiled.as_text()
    mesh_shape = dict(mesh.shape) if mesh is not None else {}
    ledger = ledger_from_hlo_text(text, step=step, mesh_shape=mesh_shape)
    ledger.peak_hbm_bytes = compiled_peak_bytes(compiled)
    return ledger


def write_ledgers(path: str, ledgers: Sequence[CommLedger]) -> None:
    """``comm_ledger.json``: ``{step_name: ledger_dict}``."""
    data = {lg.step: lg.to_dict() for lg in ledgers}
    with open(path, "w") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


def load_ledgers(path: str) -> Dict[str, CommLedger]:
    with open(path) as f:
        data = json.load(f)
    out: Dict[str, CommLedger] = {}
    for step, d in data.items():
        entries = [CommEntry(**e) for e in d.get("entries", [])]
        out[step] = CommLedger(step=step,
                               mesh_shape=d.get("mesh_shape", {}),
                               entries=entries,
                               peak_hbm_bytes=float(
                                   d.get("peak_hbm_bytes", 0.0)))
    return out
