"""Per-rank distributed flight recorder: the crash-forensics twin of the
goodput/ledger layers.

Every distributed recipe shares the classic failure mode: one rank dies or
desyncs inside a collective and the whole job hangs with zero forensics.
The obs stack explains *healthy* runs in depth and ``ft/elastic.py`` can
*react* to a dead rank, but nothing recorded what each rank was doing when
things went wrong.  This module closes that gap with three pieces:

``FlightRecorder``
    A bounded in-memory ring buffer (fixed-size ``collections.deque`` of
    compact event tuples — step begin/end, collective entry/exit with
    kind+bytes from the comm ledger, ft_events, membership-epoch changes,
    checkpoint saves, signals) that costs ~zero on the hot path: a
    ``record()`` is one tuple allocation and a deque append — no host
    sync, no I/O, no lock.  ``dump(reason)`` serializes the ring plus the
    forensic scalars (last-entered collective, last heartbeat fields,
    membership epoch, process memory, step-time p50/p95) to
    ``flightrec_rank<k>.json`` atomically (tmp + ``os.replace``) and
    never raises — it runs on death paths where a secondary failure must
    not mask the primary one.

``FlightSignalDump``
    A ``parse_signals``-compatible signal installer that dumps the ring
    and then *chains* to the previously installed handler (the
    ``PreemptionGuard._handler`` idiom), so ``--flight-rec`` composes
    with ``--preempt-signals`` on the same signal set.

``HangWatchdog``
    A daemon thread that flags a step exceeding ``max(timeout, K×p95)``
    of completed step times: it emits a ``hang`` ft_event (with the
    last-entered collective attached), records it in the ring, and dumps
    the ring pre-mortem — once per stalled step (the latch re-arms when
    the step id advances), so a genuine multi-minute stall produces one
    dump, not a flood.

``scripts/postmortem.py`` merges the per-rank dumps (aligning clocks via
the heartbeat history) into a cross-rank root-cause report: which rank
stalled first, the desync frontier (last collective each rank entered),
step skew, membership epoch at death, per-rank memory at death.
"""

from __future__ import annotations

import json
import os
import signal as _signal
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Tuple

from pytorch_distributed_tpu.obs.heartbeat import sample_process_memory

__all__ = [
    "FlightRecorder",
    "FlightSignalDump",
    "HangWatchdog",
    "DEFAULT_CAPACITY",
    "DEFAULT_HANG_TIMEOUT",
    "attach_to_metrics",
    "dump_path",
    "find_dumps",
]

DEFAULT_CAPACITY = 2048
DEFAULT_HANG_TIMEOUT = 30.0  # the max(30s, K×p95) floor
SCHEMA_VERSION = 1
_PREFIX = "flightrec_rank"


def dump_path(out_dir: str, rank: int) -> str:
    return os.path.join(out_dir, f"{_PREFIX}{int(rank)}.json")


def find_dumps(out_dir: str) -> Dict[int, str]:
    """``{rank: path}`` for every ``flightrec_rank<k>.json`` under
    ``out_dir`` (non-recursive; silent on a missing directory)."""
    out: Dict[int, str] = {}
    try:
        names = os.listdir(out_dir)
    except OSError:
        return out
    for name in sorted(names):
        if not (name.startswith(_PREFIX) and name.endswith(".json")):
            continue
        digits = name[len(_PREFIX):-len(".json")]
        if digits.isdigit():
            out[int(digits)] = os.path.join(out_dir, name)
    return out


class FlightRecorder:
    """Bounded per-rank event ring + atomic postmortem dump.

    Hot-path contract: ``record()`` and the ``step_begin``/``coll_enter``/
    ``coll_exit``/``step_end`` helpers do one deque append and a couple of
    scalar stores — no syncs, no syscalls.  All I/O lives in ``dump()``,
    which only runs on death paths (or explicitly at end of fit)."""

    def __init__(self, out_dir: str, *, rank: int = 0,
                 capacity: int = DEFAULT_CAPACITY):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.out_dir = out_dir
        self.rank = int(rank)
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self._total = 0               # events ever recorded (for drop count)
        # Current-step scalars the watchdog polls (GIL-atomic stores).
        self._step_t0: Optional[float] = None
        self._cur_step: Optional[int] = None
        self._step_times: deque = deque(maxlen=512)
        # Forensic scalars carried whole into the dump header.
        self.last_collective: Optional[Dict[str, Any]] = None
        self.last_heartbeat: Optional[Dict[str, Any]] = None
        self.membership: Dict[str, Any] = {"world": None, "epoch": 0}
        self.dump_reasons: List[str] = []

    # ------------------------------------------------------------- ring --
    def record(self, kind: str, step: Optional[int] = None,
               **fields: Any) -> None:
        """Append one compact event tuple; O(1), never blocks."""
        self._ring.append((time.time(), kind, step, fields or None))
        self._total += 1

    def step_begin(self, step: int) -> None:
        self._cur_step = step
        self._step_t0 = time.time()
        self.record("step_begin", step)

    def coll_enter(self, step: int, kind: Optional[str] = None,
                   bytes: Optional[float] = None,
                   name: Optional[str] = None) -> None:
        """Entering the compiled step's collective region.  ``kind``/
        ``bytes`` come from the comm ledger's dominant entry when the
        ``--comm-ledger`` lowering ran; None otherwise (the frontier then
        reports the step region without a collective label)."""
        self.last_collective = {
            "step": step, "kind": kind, "bytes": bytes, "name": name,
            "t": time.time(),
        }
        self.record("coll_enter", step, collective=kind, bytes=bytes)

    def coll_exit(self, step: int) -> None:
        self.record("coll_exit", step)

    def step_end(self, step: int, dt: Optional[float] = None) -> None:
        t = time.time()
        if dt is None and self._step_t0 is not None:
            dt = t - self._step_t0
        if dt is not None:
            self._step_times.append(float(dt))
        # Clear the in-step flag BEFORE the ring append so the watchdog
        # never sees a completed step as still running.
        self._step_t0 = None
        self.record("step_end", step,
                    dt=None if dt is None else round(float(dt), 6))

    def event(self, kind: str, step: Optional[int] = None,
              **fields: Any) -> None:
        """ft_events / checkpoint / remesh — same ring, explicit name for
        call sites that mirror ``MetricsLogger.log_event``."""
        self.record(kind, step, **fields)

    def heartbeat(self, fields: Dict[str, Any]) -> None:
        """Remember the last heartbeat record (scalar slot, not a ring
        entry — beats would otherwise crowd out real events)."""
        self.last_heartbeat = dict(fields)

    def set_membership(self, world: Optional[int], epoch: int) -> None:
        self.membership = {"world": world, "epoch": int(epoch)}
        self.record("membership", None, world=world, epoch=int(epoch))

    # --------------------------------------------------- watchdog reads --
    def in_step(self) -> Optional[Tuple[int, float]]:
        """``(step, elapsed_s)`` while inside a step, else None."""
        t0 = self._step_t0
        if t0 is None:
            return None
        return (self._cur_step if self._cur_step is not None else -1,
                time.time() - t0)

    def step_time_quantile(self, q: float) -> Optional[float]:
        """Quantile over completed step times; None below 5 samples (the
        watchdog then falls back to its fixed timeout floor)."""
        times = sorted(self._step_times)
        if len(times) < 5:
            return None
        idx = min(len(times) - 1, int(q * (len(times) - 1) + 0.5))
        return times[idx]

    # -------------------------------------------------------------- dump --
    def snapshot(self, reason: str) -> Dict[str, Any]:
        times = sorted(self._step_times)
        n = len(times)
        cur = self.in_step()
        return {
            "schema": SCHEMA_VERSION,
            "rank": self.rank,
            "pid": os.getpid(),
            "reason": reason,
            "t_dump": time.time(),
            "capacity": self.capacity,
            "events_total": self._total,
            "events_dropped": max(0, self._total - len(self._ring)),
            "last_collective": self.last_collective,
            "last_heartbeat": self.last_heartbeat,
            "membership": dict(self.membership),
            "in_step": (None if cur is None
                        else {"step": cur[0], "elapsed_s": round(cur[1], 6)}),
            "step_times": {
                "count": n,
                "p50": times[n // 2] if n else None,
                "p95": times[min(n - 1, int(0.95 * n))] if n else None,
            },
            "mem_bytes": sample_process_memory(),
            "events": [
                {"t": t, "kind": kind, "step": step,
                 **(fields if fields else {})}
                for (t, kind, step, fields) in list(self._ring)
            ],
        }

    def dump(self, reason: str) -> Optional[str]:
        """Atomic best-effort dump; returns the path or None on failure.
        Runs on death paths — swallows everything (a dump failure must
        never mask the primary error or re-enter a signal handler)."""
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            path = dump_path(self.out_dir, self.rank)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                # default=str: ring fields may hold device scalars or
                # other non-JSON values; a dump must never raise over one.
                json.dump(self.snapshot(reason), f, default=str)
                f.write("\n")
            os.replace(tmp, path)
            self.dump_reasons.append(reason)
            return path
        except Exception:
            return None


def attach_to_metrics(recorder: FlightRecorder, obs: Any,
                      skip: Tuple[str, ...] = ("hang",)) -> None:
    """Mirror every ``obs.log_event`` ft_event (skip / rollback / preempt /
    remesh / checkpoint, including ones emitted from inside
    ``DivergenceGuard``) into the flight ring by wrapping the bound
    method.  ``hang`` is skipped by default — the watchdog records it in
    the ring itself before calling ``log_event``."""
    orig = obs.log_event

    def log_event(kind, step=None, **fields):
        if kind not in skip:
            try:
                recorder.record(str(kind), step, **fields)
            except Exception:
                pass
        return orig(kind, step=step, **fields)

    obs.log_event = log_event


class FlightSignalDump:
    """Dump the ring on fatal/preemption signals, then chain to whatever
    handler was installed before (``PreemptionGuard`` chains the same way,
    so install order between the two does not matter)."""

    def __init__(self, recorder: FlightRecorder,
                 signals: Iterable[int] = (_signal.SIGTERM,)):
        self.recorder = recorder
        self.signals = tuple(signals)
        self._prev: Dict[int, Any] = {}
        self._installed = False

    def _handler(self, signum, frame) -> None:
        self.recorder.record("signal", None, signum=int(signum))
        self.recorder.dump(f"signal:{int(signum)}")
        prev = self._prev.get(signum)
        if callable(prev):
            prev(signum, frame)

    def install(self) -> "FlightSignalDump":
        for s in self.signals:
            self._prev[s] = _signal.signal(s, self._handler)
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for s in self.signals:
            prev = self._prev.get(s)
            _signal.signal(s, prev if prev is not None else _signal.SIG_DFL)
        self._installed = False


class HangWatchdog:
    """Collective-hang watchdog: a daemon thread flagging a step that
    exceeds ``max(timeout, k×p95)`` of completed step times.

    On firing it (1) records a ``hang`` event in the ring with the
    last-entered collective attached, (2) emits a ``hang`` ft_event via
    the metrics logger (so heartbeats carry ``last_ft=hang`` and the
    goodput/report layers see it), and (3) dumps the ring pre-mortem.
    Fires **once per stalled step** — the latch re-arms only when the
    step id advances, so there is no flapping while the stall persists."""

    def __init__(self, recorder: FlightRecorder, *,
                 obs: Any = None,
                 timeout: float = DEFAULT_HANG_TIMEOUT,
                 k: float = 4.0,
                 poll_s: Optional[float] = None):
        self.recorder = recorder
        self.obs = obs
        self.timeout = float(timeout)
        self.k = float(k)
        # Poll fast enough to catch a short drill timeout, slow enough to
        # stay invisible next to a 30s production floor.
        self.poll_s = (poll_s if poll_s is not None
                       else max(0.02, min(0.5, self.timeout / 8.0)))
        self.hangs = 0
        self._flagged_step: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def threshold(self) -> float:
        p95 = self.recorder.step_time_quantile(0.95)
        if p95 is None:
            return self.timeout
        return max(self.timeout, self.k * p95)

    def check(self, now_elapsed: Optional[Tuple[int, float]] = None) -> bool:
        """One watchdog evaluation; split out so tests can drive it
        without waiting on the thread.  Returns True when it fired."""
        cur = (self.recorder.in_step() if now_elapsed is None
               else now_elapsed)
        if cur is None:
            return False
        step, elapsed = cur
        if step == self._flagged_step:
            return False              # already fired for this stall
        if elapsed <= self.threshold():
            return False
        self._flagged_step = step
        self.hangs += 1
        coll = self.recorder.last_collective or {}
        self.recorder.record(
            "hang", step, elapsed_s=round(elapsed, 3),
            threshold_s=round(self.threshold(), 3),
            collective=coll.get("kind"))
        if self.obs is not None:
            try:
                self.obs.log_event(
                    "hang", step=step, elapsed_s=round(elapsed, 3),
                    collective=coll.get("kind") or "",
                    collective_bytes=coll.get("bytes") or 0)
            except Exception:
                pass                  # forensics must not kill the run
        self.recorder.dump("hang")
        return True

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.check()
            except Exception:
                pass

    def start(self) -> "HangWatchdog":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="flightrec-hang-watchdog", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=2.0)
        self._thread = None
