"""Trace annotation helpers: one idiom for host spans and in-graph names.

``scope(name)`` composes ``jax.profiler.TraceAnnotation`` (a host-side
XPlane span around whatever runs inside the ``with``) with
``jax.named_scope`` (HLO op-name metadata attached to every op *traced*
inside it).  Used around the driver's step call it marks the host
timeline; used inside a jitted function (train/steps.py forward/optimizer
phases, the pipeline schedules' per-stage tick regions) it makes XPlane
self-time attribute to named regions — ``pp_stage_fwd`` instead of
``fusion.1234`` — which is what turns ``scripts/profile_trace.py`` output
into per-stage evidence.

``ProfileWindow`` drives ``jax.profiler.start_trace``/``stop_trace`` from
epoch/step windows so a trace can capture steady state, not just the
warm-up epoch the seed hard-coded.  ``capture(dir)`` is its one-shot
contextmanager form for scripts that just want "trace this block" —
the ``scripts/profile_*.py`` family all funnel through it so there is
exactly one start/stop_trace call site outside the trainers.
"""

from __future__ import annotations

import contextlib
from typing import Optional, Tuple

import jax


@contextlib.contextmanager
def scope(name: str):
    """Host TraceAnnotation + in-graph named_scope under one name."""
    with jax.profiler.TraceAnnotation(name), jax.named_scope(name):
        yield


@contextlib.contextmanager
def capture(trace_dir: str):
    """One-shot profiler capture: trace everything inside the ``with``.

    The single-segment form of ``ProfileWindow`` — same start/stop pairing,
    no epoch/step bookkeeping.  XPlane files land under ``trace_dir`` and
    can be decoded with ``obs.timeline.find_xplane_files``/``parse_xspace``
    or ``scripts/obs_timeline.py``.
    """
    jax.profiler.start_trace(trace_dir)
    try:
        yield trace_dir
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Decorator form of ``scope`` for whole functions."""

    def wrap(fn):
        import functools

        @functools.wraps(fn)
        def inner(*a, **kw):
            with scope(name):
                return fn(*a, **kw)

        return inner

    return wrap


def parse_span(spec: Optional[str]) -> Optional[Tuple[int, int]]:
    """``"5"`` → (5, 6); ``"10:20"`` → (10, 20) — python half-open ranges."""
    if spec is None or spec == "":
        return None
    parts = str(spec).split(":")
    try:
        if len(parts) == 1:
            lo = int(parts[0])
            return (lo, lo + 1)
        if len(parts) == 2:
            lo, hi = int(parts[0]), int(parts[1])
            if hi <= lo:
                raise ValueError
            return (lo, hi)
    except ValueError:
        pass
    raise ValueError(
        f"bad span {spec!r}: expected 'N' or 'LO:HI' with HI > LO")


class ProfileWindow:
    """Epoch/step-windowed profiler control.

    - no windows: trace the first trained epoch (the seed behavior);
    - ``epochs='A'`` / ``'A:B'``: trace those epochs (one trace segment per
      epoch — ``stop_trace`` runs at each epoch end);
    - ``steps='I'`` / ``'I:J'``: within an active epoch, trace only that
      in-epoch step range (steady-state capture past compilation and
      cache warm-up).
    """

    def __init__(self, profile_dir: Optional[str], epochs: Optional[str] = None,
                 steps: Optional[str] = None, start_epoch: int = 0):
        self.dir = profile_dir
        self.epochs = parse_span(epochs)
        self.steps = parse_span(steps)
        self.start_epoch = start_epoch
        self._tracing = False

    def _epoch_active(self, epoch: int) -> bool:
        if not self.dir:
            return False
        if self.epochs is None:
            return epoch == self.start_epoch
        return self.epochs[0] <= epoch < self.epochs[1]

    def epoch_begin(self, epoch: int) -> None:
        if self.steps is None and self._epoch_active(epoch):
            self._start()

    def step_begin(self, epoch: int, step: int) -> None:
        """Call at the top of every train step (cheap when inactive)."""
        if self.steps is None:
            return
        if self._epoch_active(epoch) and self.steps[0] <= step < self.steps[1]:
            self._start()
        else:
            self._stop()

    def epoch_end(self) -> bool:
        """Stop an open trace segment; True when one was written."""
        return self._stop()

    def _start(self) -> None:
        if not self._tracing:
            jax.profiler.start_trace(self.dir)
            self._tracing = True

    def _stop(self) -> bool:
        if self._tracing:
            jax.profiler.stop_trace()
            self._tracing = False
            return True
        return False
