"""LM held-out eval: loss/ppl/acc consistency, best tracking, recipe flag."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.models.transformer import TransformerLM
from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
from pytorch_distributed_tpu.train.lm import (
    LMTrainer,
    SyntheticTokenDataset,
    make_lm_eval_step,
)


def _mesh():
    return build_mesh(MeshSpec(("data",), (8,)), jax.devices()[:8])


def _tiny_model():
    return TransformerLM(vocab_size=64, d_model=32, n_heads=4, n_layers=1)


def test_eval_step_sums_are_exact():
    mesh = _mesh()
    model = _tiny_model()
    ds = SyntheticTokenDataset(64, 32, 64, seed=0)
    t = LMTrainer(model, mesh, ds, batch_size=8, eval_dataset=ds,
                  eval_batches=2)
    loss, ppl, acc = t.evaluate()
    assert math.isfinite(loss) and ppl == pytest.approx(math.exp(loss), rel=1e-6)
    assert 0.0 <= acc <= 100.0
    # exact: per-batch sums add to eval over a manual pass
    totals = 0.0
    count = 0.0
    for i in range(2):
        tokens = jax.device_put(ds.batch(i, 8), t.token_sharding)
        sums = t._eval_fn(t.state, tokens)
        totals += float(sums["loss_sum"])
        count += float(sums["count"])
    assert loss == pytest.approx(totals / count, rel=1e-6)


def test_fit_with_periodic_eval_tracks_best(tmp_path, capsys):
    mesh = _mesh()
    model = _tiny_model()
    train_ds = SyntheticTokenDataset(64, 32, 64, seed=0)
    eval_ds = SyntheticTokenDataset(16, 32, 64, seed=1)
    t = LMTrainer(model, mesh, train_ds, batch_size=8, lr=1e-2,
                  eval_dataset=eval_ds, eval_every=2, eval_batches=1,
                  checkpoint_dir=str(tmp_path))
    t.fit(5, print_freq=2)
    out = capsys.readouterr().out
    # periodic at steps 2 and 4, plus the final (step 5 is off-boundary)
    assert out.count("* Eval loss") == 3
    assert math.isfinite(t.best_ppl)
    assert (tmp_path / "checkpoint.msgpack").exists()

    # last step ON an eval boundary: the interval eval doubles as the final
    # one (no duplicate pass), and the final state still counts as best when
    # it ties the best seen.
    t2 = LMTrainer(_tiny_model(), mesh, train_ds, batch_size=8, lr=1e-2,
                   eval_dataset=eval_ds, eval_every=2, eval_batches=1)
    t2.fit(4, print_freq=2)
    out2 = capsys.readouterr().out
    assert out2.count("* Eval loss") == 2


def test_recipe_eval_flags(capsys):
    from pytorch_distributed_tpu.recipes import lm_pretrain

    lm_pretrain.main([
        "--vocab", "64", "--d-model", "32", "--n-heads", "4",
        "--n-layers", "1", "--seq-len", "32", "-b", "8", "--steps", "3",
        "--eval-batches", "1", "-p", "1", "--dataset-length", "64",
    ])
    out = capsys.readouterr().out
    assert "* Eval loss" in out and "* Final loss" in out


def test_text_file_dataset_real_bytes(tmp_path):
    from pytorch_distributed_tpu.train.lm import TextFileDataset

    (tmp_path / "a.txt").write_bytes(b"hello world " * 50)
    (tmp_path / "b.txt").write_bytes(b"goodbye " * 40)
    ds = TextFileDataset(str(tmp_path / "*.txt"), seq_len=32)
    assert len(ds) >= 1
    s = ds[0]
    assert s.shape == (32,) and s.dtype == np.int32
    assert (s >= 0).all() and (s < 256).all()
    assert bytes(s[:5].astype(np.uint8)) == b"hello"
    # span carves disjoint train/eval windows
    train = TextFileDataset(str(tmp_path / "*.txt"), 32, span=(0.0, 0.9))
    ev = TextFileDataset(str(tmp_path / "*.txt"), 32, span=(0.9, 1.0))
    assert len(train.data) + len(ev.data) >= len(ds.data) - 1


def test_lm_pretrain_on_real_text(capsys, tmp_path):
    """Byte-level LM on actual files through the recipe: repeated text is
    learnable, loss must drop."""
    from pytorch_distributed_tpu.recipes import lm_pretrain

    (tmp_path / "corpus.txt").write_bytes(b"the quick brown fox " * 300)
    final = lm_pretrain.main([
        "--text-glob", str(tmp_path / "*.txt"),
        "--d-model", "32", "--n-heads", "2", "--n-layers", "1",
        "--seq-len", "32", "-b", "8", "--steps", "15", "--lr", "0.1",
        "-p", "4", "--precision", "fp32", "--eval-batches", "1",
    ])
    out = capsys.readouterr().out
    assert "* Eval loss" in out
    first = float(out.split("Loss ")[1].split(" ")[0])
    assert final < first


def test_warmup_cosine_schedule_shape():
    from pytorch_distributed_tpu.train.lm import warmup_cosine_lr

    sched = warmup_cosine_lr(1.0, warmup_steps=10, total_steps=110,
                             min_frac=0.1)
    assert sched(0) == pytest.approx(0.1)      # warmup start
    assert sched(9) == pytest.approx(1.0)      # warmup end
    assert sched(10) == pytest.approx(1.0)     # cosine start
    assert sched(60) == pytest.approx(0.55, abs=0.02)  # mid-decay
    assert sched(109) == pytest.approx(0.1, abs=0.01)  # floor
    assert sched(500) == pytest.approx(0.1, abs=1e-6)  # clamped past end


def test_clip_grad_norm_bounds_update():
    """With an absurdly small clip norm the parameter update magnitude is
    bounded by lr * clip; without clipping it is much larger."""
    from pytorch_distributed_tpu.train.lm import make_lm_train_step
    from pytorch_distributed_tpu.parallel.tp import replicated_like, shard_state
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState

    mesh = _mesh()
    model = _tiny_model()
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(8, 32)).astype(np.int32))

    def run(clip):
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 32), jnp.int32))["params"]
        # host copy first: the jitted step donates (consumes) its input state
        orig = jax.tree_util.tree_map(np.asarray, params)
        sp = replicated_like(params)
        state = shard_state(
            TrainState.create({"params": params}, sgd_init(params)), sp, mesh)
        step = make_lm_train_step(model, mesh, sp, weight_decay=0.0,
                                  clip_grad_norm=clip)
        with mesh:
            new_state, _ = step(state, tokens, jnp.float32(1.0))
        delta = np.sqrt(sum(
            float(jnp.sum((a - b) ** 2)) for a, b in zip(
                jax.tree_util.tree_leaves(new_state.params),
                jax.tree_util.tree_leaves(orig))))
        return float(delta)

    clipped = run(1e-3)
    unclipped = run(0.0)
    assert clipped <= 1e-3 + 1e-6   # ||Δparams|| = lr * ||clipped grads||
    assert unclipped > 10 * clipped


def test_lm_accum_matches_unaccumulated():
    """accum_steps=2 must produce the same update as the plain step at the
    same global batch (equal-size token-mean microbatches; fp reassociation
    is the only difference)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pytorch_distributed_tpu.parallel.tp import replicated_like, shard_state
    from pytorch_distributed_tpu.train.lm import make_lm_train_step
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState

    mesh = _mesh()
    model = _tiny_model()
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 16), jnp.int32))["params"]
    specs = replicated_like(params)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, 64, size=(16, 16)).astype(np.int32))
    out = {}
    with mesh:
        toks = jax.device_put(tokens, NamedSharding(mesh, P("data", None)))
        for accum in (1, 2, 4):
            p = jax.tree_util.tree_map(jnp.array, params)
            state = shard_state(
                TrainState.create({"params": p}, sgd_init(p)), specs, mesh)
            step = make_lm_train_step(model, mesh, specs, accum_steps=accum)
            state2, metrics = step(state, toks, jnp.float32(0.05))
            out[accum] = (float(metrics["loss"]), float(metrics["acc"]),
                          jax.device_get(state2.params))
    for accum in (2, 4):
        assert out[accum][0] == pytest.approx(out[1][0], rel=1e-5)
        assert out[accum][1] == pytest.approx(out[1][1], rel=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(out[1][2]),
                        jax.tree_util.tree_leaves(out[accum][2])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-6)


def test_lm_accum_rejects_manual_grads_model():
    from pytorch_distributed_tpu.train.lm import make_lm_train_step

    class FakePipelined:
        def has_manual_grads(self):
            return True

    mesh = _mesh()
    with pytest.raises(ValueError, match="1F1B"):
        make_lm_train_step(FakePipelined(), mesh, {}, accum_steps=2)


def test_prefetch_modes_produce_identical_training():
    """prefetch=2 (AsyncFeeder) and prefetch=0 (synchronous baseline) must
    consume identical batch streams — same final loss and params."""
    mesh = _mesh()
    results = {}
    for prefetch in (0, 2):
        model = TransformerLM(vocab_size=32, d_model=32, n_heads=2,
                              n_layers=1)
        ds = SyntheticTokenDataset(16, 16, 32)
        with mesh:
            t = LMTrainer(model, mesh, ds, batch_size=8, lr=0.05,
                          prefetch=prefetch)
            final = t.fit(6, print_freq=100)
            results[prefetch] = (final,
                                 jax.device_get(t.state.params))
    assert results[0][0] == results[2][0]
    for a, b in zip(jax.tree_util.tree_leaves(results[0][1]),
                    jax.tree_util.tree_leaves(results[2][1])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
