"""Short CI versions of the round-3 convergence oracles.

- experiments/convergence_hard.py: the 100-class low-SNR top-1 oracle —
  here a 20-class / 2-epoch miniature pinning that (a) the task is NOT
  saturating, (b) fp32 and bf16 agree within noise while both learn.
- experiments/lm_text.py: real-text byte-LM held-out perplexity must drop.

The full runs (committed RESULTS_convergence_hard.json /
RESULTS_lm_text.json) use the same code paths at larger scale."""

import json
import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def test_hard_oracle_miniature(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "experiments"))
    try:
        import convergence_hard as ch
    finally:
        sys.path.pop(0)

    # Miniature: a 20-class hue wheel with the same jittered-hue generator,
    # 2 epochs — small enough for CI, jitter keeps it off the ceiling.
    ch.CLASSES = 20
    ch.PER_CLASS_TRAIN, ch.PER_CLASS_VAL = 12, 4
    ch.EPOCHS, ch.BATCH, ch.IMAGE = 2, 40, 32

    root = str(tmp_path / "data")
    ch.make_dataset(root)
    curves = {}
    for name, precision in (("fp32", "fp32"), ("bf16", "bf16")):
        curves[name] = ch.run_config(root, str(tmp_path), name, precision,
                                     1, False)
    for name, curve in curves.items():
        # 2 epochs only in CI: ≥3× chance = learning; the committed full
        # run (RESULTS_convergence_hard.json) shows the real curve.
        assert curve[-1] > 3 * 100.0 / ch.CLASSES, (name, curve)
        assert curve[-1] < 97.0, (name, curve)  # doesn't saturate
    # Full-size round-4 curves put fp32/bf16 within 0.4 points at this
    # epoch; the miniature's 80-image val set quantizes top-1 in 1.25-point
    # steps, so allow ±9 samples of small-sample noise (measured gaps range
    # up to 8.75 across jax/XLA versions) while remaining falsifiable
    # (the old ≤15 at ~12% values was near-vacuous — VERDICT r3 weak #3).
    assert abs(curves["fp32"][-1] - curves["bf16"][-1]) <= 11.25, curves


def test_hue_oracle_estimator(tmp_path):
    """The achievable-ceiling measurement tool itself
    (experiments/convergence_hard.py oracle_estimator_top1): the
    known-generator hue reader must score far above chance on a fresh
    jittered dataset and near the analytic ceiling — if it ever reads
    ~chance, the generator or the inversion broke, and achievable_pct in
    RESULTS_convergence_hard.json would be meaningless."""
    sys.path.insert(0, os.path.join(REPO, "experiments"))
    try:
        import convergence_hard as ch
    finally:
        sys.path.pop(0)

    ch.CLASSES = 20
    ch.PER_CLASS_TRAIN, ch.PER_CLASS_VAL = 1, 12
    root = str(tmp_path / "data")
    ch.make_dataset(root)
    top1 = ch.oracle_estimator_top1(root)
    chance = 100.0 / ch.CLASSES
    assert top1 > 5 * chance, (top1, chance)
    # within noise of the analytic ceiling (binomial on 240 samples)
    assert abs(top1 - ch.CEILING) < 15.0, (top1, ch.CEILING)


def test_lm_text_miniature(tmp_path):
    out_path = str(tmp_path / "lm_text.json")
    env = dict(os.environ)
    env.update(LMTEXT_SEQ="128", LMTEXT_D="64", LMTEXT_STEPS="60",
               LMTEXT_EVAL_EVERY="30", LMTEXT_OUT=out_path,
               PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "experiments", "lm_text.py")],
        env=env, capture_output=True, text=True, timeout=1200,
    )
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    with open(out_path) as f:
        out = json.load(f)
    assert out["curve"][-1]["ppl"] < out["initial"]["ppl"]
