"""SyncBN (--sync-bn): cross-replica BN moments in the explicit-collectives
step must reproduce GSPMD's global-batch BN semantics exactly.

The round-4 hard-oracle matrix measured the per-shard-BN explicit leg
converging 18 points under the GSPMD legs at batch 4/device
(RESULTS_convergence_hard.json); this is the framework-level fix — the
torch capability analogue is ``nn.SyncBatchNorm`` (reference recipes train
unsynced BN under DDP, distributed.py:147-148, which is the default here).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from pytorch_distributed_tpu.models import create_model
from pytorch_distributed_tpu.ops.fused_bn import FusedBatchNormAct, _bn_act
from pytorch_distributed_tpu.train.optim import sgd_init
from pytorch_distributed_tpu.train.state import TrainState
from pytorch_distributed_tpu.train.steps import make_train_step

N_DEV = 8


def _mesh():
    return Mesh(np.asarray(jax.devices()[:N_DEV]), ("data",))


def test_bn_act_syncbn_matches_full_batch():
    """shard_map'd _bn_act(axis_name='data') on 8 shards == single-call
    _bn_act on the concatenated batch — forward AND backward."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(1.5, 2.0, size=(16, 4, 4, 3)), jnp.float32)
    gamma = jnp.asarray(rng.normal(1, 0.1, size=(3,)), jnp.float32)
    beta = jnp.asarray(rng.normal(0, 0.1, size=(3,)), jnp.float32)

    def full_loss(x, g, b):
        o, _, _ = _bn_act(x, g, b, 1e-5, True)
        return jnp.sum(o * o)

    def sharded_loss(x, g, b):
        def local(xs, g, b):
            o, _, _ = _bn_act(xs, g, b, 1e-5, True, "data")
            # per-shard partial loss; psum -> global scalar
            return jax.lax.psum(jnp.sum(o * o), "data")

        return shard_map(
            local, mesh=_mesh(), in_specs=(P("data"), P(), P()),
            out_specs=P(), check_vma=False,
        )(x, g, b)

    want, wg = jax.value_and_grad(full_loss, argnums=(0, 1, 2))(x, gamma, beta)
    got, gg = jax.value_and_grad(sharded_loss, argnums=(0, 1, 2))(
        x, gamma, beta)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)
    for a, b_ in zip(gg, wg):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)


def test_explicit_syncbn_step_matches_gspmd():
    """One optimizer step: explicit-collectives + sync_bn == GSPMD (whose
    BN is global-batch by construction) — params, stats, and metrics."""
    mesh = _mesh()
    kw = dict(num_classes=10, dtype=jnp.float32)
    model_sync = create_model("resnet18", bn_axis_name="data", **kw)
    model_plain = create_model("resnet18", **kw)

    sample = jnp.zeros((1, 32, 32, 3), jnp.float32)
    variables = model_plain.init(jax.random.PRNGKey(0), sample, train=False)
    state0 = lambda: TrainState.create(  # noqa: E731
        jax.tree_util.tree_map(jnp.copy, variables),
        sgd_init(variables["params"]))

    rng = np.random.default_rng(1)
    batch = {
        "images": jnp.asarray(
            rng.normal(0, 1, size=(16, 32, 32, 3)), jnp.float32),
        "labels": jnp.asarray(rng.integers(0, 10, size=(16,)), jnp.int32),
        "weights": jnp.ones((16,), jnp.float32),
    }
    lr = jnp.float32(0.1)

    step_sync = make_train_step(model_sync, mesh, explicit_collectives=True)
    step_gspmd = make_train_step(model_plain, mesh)
    s1, m1 = step_sync(state0(), batch, lr)
    s2, m2 = step_gspmd(state0(), batch, lr)

    for k in m1:
        np.testing.assert_allclose(
            float(m1[k]), float(m2[k]), rtol=1e-4, atol=1e-4)
    flat1 = jax.tree_util.tree_leaves_with_path(s1.params)
    flat2 = dict(jax.tree_util.tree_leaves_with_path(s2.params))
    for path, v in flat1:
        np.testing.assert_allclose(
            np.asarray(v), np.asarray(flat2[path]), rtol=5e-3, atol=5e-3,
            err_msg=jax.tree_util.keystr(path))
    stats1 = jax.tree_util.tree_leaves_with_path(s1.batch_stats)
    stats2 = dict(jax.tree_util.tree_leaves_with_path(s2.batch_stats))
    for path, v in stats1:
        np.testing.assert_allclose(
            np.asarray(v), np.asarray(stats2[path]), rtol=1e-3, atol=1e-3,
            err_msg=jax.tree_util.keystr(path))


def test_pershard_bn_differs_from_syncbn():
    """Sanity: WITHOUT sync_bn the explicit step's BN statistics are
    per-shard, so its first-step metrics differ from GSPMD's on a batch
    with shard-skewed distribution (the round-4 convergence-gap mechanism
    in miniature)."""
    mesh = _mesh()
    kw = dict(num_classes=10, dtype=jnp.float32)
    model_plain = create_model("resnet18", **kw)

    sample = jnp.zeros((1, 32, 32, 3), jnp.float32)
    variables = model_plain.init(jax.random.PRNGKey(0), sample, train=False)
    mk_state = lambda: TrainState.create(  # noqa: E731
        jax.tree_util.tree_map(jnp.copy, variables),
        sgd_init(variables["params"]))

    rng = np.random.default_rng(2)
    # shard-skewed inputs: shard i centered at i (BN per-shard mean removes
    # the skew; global BN does not)
    imgs = np.stack([
        rng.normal(i % N_DEV, 1, size=(32, 32, 3)) for i in range(16)
    ]).astype(np.float32)
    batch = {
        "images": jnp.asarray(imgs),
        "labels": jnp.asarray(rng.integers(0, 10, size=(16,)), jnp.int32),
        "weights": jnp.ones((16,), jnp.float32),
    }
    lr = jnp.float32(0.1)
    step_nosync = make_train_step(
        model_plain, mesh, explicit_collectives=True)
    step_gspmd = make_train_step(model_plain, mesh)
    _, m_no = step_nosync(mk_state(), batch, lr)
    _, m_gs = step_gspmd(mk_state(), batch, lr)
    assert abs(float(m_no["loss"]) - float(m_gs["loss"])) > 1e-4


def test_sync_bn_trainer_gates():
    """--sync-bn config gates: conflicts with --fused-convbn (no synced
    fold kernel), rejected for BN-free archs; accepted quietly under
    GSPMD (documented no-op)."""
    import pytest

    from pytorch_distributed_tpu.train.config import Config
    from pytorch_distributed_tpu.train.trainer import Trainer

    def cfg(**kw):
        kw.setdefault("arch", "resnet18")
        return Config(synthetic=True, synthetic_length=16, batch_size=16,
                      image_size=32, num_classes=4, epochs=1, **kw)

    with pytest.raises(ValueError, match="mutually exclusive"):
        Trainer(cfg(sync_bn=True, fused_convbn=True, arch="resnet50"),
                explicit_collectives=True)
    with pytest.raises(ValueError, match="no BatchNorm"):
        Trainer(cfg(sync_bn=True, arch="alexnet"),
                explicit_collectives=True)
    # plain VGG carries the field (the *_bn variants share the class) but
    # has no BN layers — must refuse rather than silently no-op
    with pytest.raises(ValueError, match="no BatchNorm"):
        Trainer(cfg(sync_bn=True, arch="vgg11"),
                explicit_collectives=True)


def test_explicit_syncbn_step_matches_gspmd_flax_bn_model():
    """The flax-BatchNorm(axis_name) path (zoo-wide --sync-bn, torch
    SyncBatchNorm is model-agnostic): one explicit+sync step on
    shufflenet_v2 (dropout-free, so the two formulations' rng streams
    cannot diverge the comparison) == one GSPMD step (global-batch BN)."""
    mesh = _mesh()
    kw = dict(num_classes=10, dtype=jnp.float32)
    model_sync = create_model("shufflenet_v2_x0_5", bn_axis_name="data",
                              **kw)
    model_plain = create_model("shufflenet_v2_x0_5", **kw)

    sample = jnp.zeros((1, 32, 32, 3), jnp.float32)
    variables = model_plain.init(jax.random.PRNGKey(0), sample, train=False)
    mk_state = lambda: TrainState.create(  # noqa: E731
        jax.tree_util.tree_map(jnp.copy, variables),
        sgd_init(variables["params"]))

    rng = np.random.default_rng(4)
    batch = {
        "images": jnp.asarray(
            rng.normal(0, 1, size=(16, 32, 32, 3)), jnp.float32),
        "labels": jnp.asarray(rng.integers(0, 10, size=(16,)), jnp.int32),
        "weights": jnp.ones((16,), jnp.float32),
    }
    lr = jnp.float32(0.1)
    s1, m1 = make_train_step(model_sync, mesh, explicit_collectives=True)(
        mk_state(), batch, lr)
    s2, m2 = make_train_step(model_plain, mesh)(mk_state(), batch, lr)
    for k in m1:
        np.testing.assert_allclose(
            float(m1[k]), float(m2[k]), rtol=1e-4, atol=1e-4)
    got = jax.tree_util.tree_leaves_with_path(s1.params)
    want = dict(jax.tree_util.tree_leaves_with_path(s2.params))
    for path, v in got:
        np.testing.assert_allclose(
            np.asarray(v), np.asarray(want[path]), rtol=5e-3, atol=5e-3,
            err_msg=jax.tree_util.keystr(path))


def test_sync_bn_axis_name_disables_convbn_fold():
    """fused_convbn + sync BN: the fold gate must reject (no synced-stats
    Pallas kernel) and fall back to the unfused composition — same
    numerics as the unfused sync model."""
    kw = dict(num_classes=10, dtype=jnp.float32)
    m_fold = create_model("resnet50", fused_convbn=True,
                          bn_axis_name="data", **kw)
    m_plain = create_model("resnet50", bn_axis_name="data", **kw)
    sample = jnp.zeros((2, 32, 32, 3), jnp.float32)
    v1 = m_fold.init(jax.random.PRNGKey(0), sample, train=False)
    v2 = m_plain.init(jax.random.PRNGKey(0), sample, train=False)
    # identical param trees (fold would rename/restructure nothing, but a
    # silently-active fold with dropped axis_name would diverge in train
    # mode under shard_map; structural equality pins the fallback)
    assert jax.tree_util.tree_structure(v1) == jax.tree_util.tree_structure(v2)

    def fwd(model, v, x):
        def local(xs):
            return model.apply(v, xs, train=True, mutable=["batch_stats"])[0]

        return shard_map(local, mesh=_mesh(), in_specs=P("data"),
                         out_specs=P("data"), check_vma=False)(x)

    x = jnp.asarray(np.random.default_rng(3).normal(
        0, 1, size=(16, 32, 32, 3)), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(fwd(m_fold, v1, x)), np.asarray(fwd(m_plain, v2, x)),
        rtol=1e-5, atol=1e-5)
