"""Communication ledger + timeline (obs/comms.py, obs/timeline.py).

Layers under test:
- the wire-byte conventions (pure arithmetic, no jax);
- static ledger vs analytic model parity: for the fenced DP/TP LM steps
  and the GSPMD image step, the ledger extracted from the compiled HLO
  must land within ±15% of ``obs.flops``'s analytic per-step byte
  estimates (the ISSUE-7 acceptance fence) — lowerings come off the
  session-shared ``get_lowering`` fixture, so this suite adds zero
  compiles beyond test_shardlint's sweep;
- XPlane round-trip on a *real* CPU profiler capture: ``trace.capture``
  + ``trace.scope`` markers in, per-step spans and comm/compute windows
  out of the stdlib decoder;
- the ``obs_report --diff`` comm fence: a planted exposed-comm
  regression (identical step time) must exit 1;
- cross-rank merge: two synthetic skewed captures + heartbeat clocks
  must align to sub-µs in the merged Chrome trace;
- ``scripts/obs_timeline.py --selftest`` end to end (separate process).
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest

from pytorch_distributed_tpu.analysis import core
from pytorch_distributed_tpu.obs import comms, flops, timeline, trace

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "scripts"))

import obs_report  # noqa: E402

_LM = core._LM


# ------------------------------------------------------- wire conventions

def test_wire_byte_conventions():
    b = 1024
    assert comms.wire_bytes("all-reduce", b, 4) == 2 * 3 / 4 * b
    assert comms.wire_bytes("all-gather", b, 4) == 3 / 4 * b
    assert comms.wire_bytes("reduce-scatter", b, 4) == 3 * b
    assert comms.wire_bytes("all-to-all", b, 4) == 3 / 4 * b
    assert comms.wire_bytes("collective-permute", b, 4) == b
    # single-participant groups move nothing
    for kind in ("all-reduce", "all-gather", "collective-permute"):
        assert comms.wire_bytes(kind, b, 1) == 0


# ------------------------------------------- ledger vs analytic model (±15%)

def test_ledger_dp_parity(get_lowering):
    low = get_lowering("lm_train_dp")
    ledger = comms.ledger_from_hlo_text(low.text, step=low.name,
                                        mesh_shape=low.mesh_shape)
    assert ledger.count > 0 and ledger.total_bytes > 0
    pred = flops.lm_comm_bytes(_LM["vocab"], _LM["d_model"], 1,
                               _LM["batch"], _LM["seq"], dp=4, tp=1)
    residual = flops.comm_residual_pct(pred.total_bytes, ledger.total_bytes)
    assert residual <= 15.0, (pred.total_bytes, ledger.total_bytes, residual)
    # scope attribution: the gradient sync must land in the backward phase
    phases = ledger.by_phase()
    assert "backward" in phases, phases
    assert phases["backward"]["bytes"] > 0.9 * ledger.total_bytes, phases


def test_ledger_tp_parity(get_lowering):
    low = get_lowering("lm_fused_ce_tp")
    ledger = comms.ledger_from_hlo_text(low.text, step=low.name,
                                        mesh_shape=low.mesh_shape)
    pred = flops.lm_comm_bytes(_LM["vocab"], _LM["d_model"], 1,
                               _LM["batch"], _LM["seq"], dp=2, tp=2,
                               fused_ce=True)
    residual = flops.comm_residual_pct(pred.total_bytes, ledger.total_bytes)
    assert residual <= 15.0, (pred.total_bytes, ledger.total_bytes, residual)
    # Megatron-style TP must show the head-boundary permutes, not just
    # psums — the kind mix is part of the fence
    kinds = ledger.by_kind()
    assert "collective-permute" in kinds, kinds
    assert "all-reduce" in kinds, kinds


def test_ledger_image_parity(get_lowering):
    low = get_lowering("train_image_gspmd")
    ledger = comms.ledger_from_hlo_text(low.text, step=low.name,
                                        mesh_shape=low.mesh_shape)
    state = low.args[0]
    params = sum(int(x.size) for x in jax.tree_util.tree_leaves(state.params))
    pred = flops.image_comm_bytes(params, dp=4)
    residual = flops.comm_residual_pct(pred.total_bytes, ledger.total_bytes)
    assert residual <= 15.0, (pred.total_bytes, ledger.total_bytes, residual)


def test_ledger_roundtrips_through_json(get_lowering, tmp_path):
    low = get_lowering("lm_train_dp")
    ledger = comms.ledger_from_hlo_text(low.text, step=low.name,
                                        mesh_shape=low.mesh_shape)
    path = str(tmp_path / "comm_ledger.json")
    comms.write_ledgers(path, [ledger])
    back = comms.load_ledgers(path)[low.name]
    assert back.total_bytes == ledger.total_bytes
    assert back.count == ledger.count
    assert back.by_kind() == ledger.by_kind()
    fields = back.metrics_fields()
    assert fields["model_comm_bytes"] == ledger.total_bytes
    assert fields["collective_count"] == ledger.count


# ----------------------------------------------- XPlane round-trip (real)

def test_xplane_roundtrip_real_capture(tmp_path):
    """Capture a real (CPU) profiler trace through the shared
    ``trace.capture`` path and decode it with the stdlib XPlane parser:
    the ``trace.scope`` step markers and device op spans must survive the
    round trip and window into per-step stats."""
    f = jax.jit(lambda x: (x @ x).sum())
    x = jnp.ones((256, 256), jnp.float32)
    float(f(x))  # compile outside the capture
    d = str(tmp_path / "trace")
    with trace.capture(d):
        for _ in range(3):
            with trace.scope("profile_step"):
                float(f(x))
    files = timeline.find_xplane_files(d)
    assert files, f"no xplane.pb under {d}"
    tl = timeline.parse_xspace(files[-1])
    assert tl.spans, "decoder produced no spans from a real capture"
    markers = tl.annotations("profile_step")
    assert len(markers) == 3, [s.name for s in tl.spans][:40]
    assert all(m.dur_ns > 0 for m in markers)
    assert any(s.is_xla_op() for s in tl.spans)
    assert tl.device_lines(), "no device stream carried XLA ops"
    stats = timeline.analyze_steps(tl, annotation="profile_step")
    assert stats, "no per-step windows"
    assert {s.step for s in stats} <= {0, 1, 2}
    for s in stats:
        assert s.compute_ns > 0
        assert 0 <= s.overlap_ns <= min(s.comm_ns, s.compute_ns) + 1e-9
    agg = timeline.aggregate_steps(stats)
    assert agg["steps"] >= 1 and agg["comm_ms_mean"] >= 0.0


# ------------------------------------------------ diff fence (exit code 1)

def _write_run(path, exposed_ms):
    from pytorch_distributed_tpu.obs.metrics import MetricsLogger

    with MetricsLogger(path, flush_every=50) as log:
        for i in range(30):
            log.log_step(i, step_time=0.010, n_items=128, lr=0.1,
                         extra={"model_comm_bytes": 66952.0,
                                "comm_wire_bytes": 100428.0,
                                "exposed_comm_ms": exposed_ms,
                                "overlap_pct": 60.0})


def test_diff_exit_1_on_planted_exposed_comm_regression(tmp_path, capsys):
    """The ISSUE-7 acceptance fence: identical step time, but collectives
    stopped hiding under compute — ``obs_report --diff`` must exit 1."""
    base = str(tmp_path / "base.jsonl")
    bad = str(tmp_path / "bad.jsonl")
    _write_run(base, exposed_ms=0.20)
    _write_run(bad, exposed_ms=0.55)
    rc = obs_report.main(["--diff", base, bad])
    out = capsys.readouterr().out
    assert rc == 1, out
    assert "REGRESS" in out and "exposed_comm_ms" in out
    # same run against itself is clean; json mode agrees with the rc
    assert obs_report.main(["--diff", base, base]) == 0
    capsys.readouterr()
    rc_json = obs_report.main(["--diff", base, bad, "--format", "json"])
    js = json.loads(capsys.readouterr().out)
    assert rc_json == 1 and js["overall"] == "REGRESS"
    by_name = {r["metric"]: r for r in js["metrics"]}
    assert by_name["exposed_comm_ms"]["verdict"] == "REGRESS"
    assert by_name["step_time_p50"]["verdict"] == "PASS"


# ------------------------------------------- cross-rank clock alignment

def _synthetic_capture(base_ns):
    return [{
        "name": "/host:CPU",
        "lines": [{
            "name": "tf_XLATfrtCpuClient/0",
            "timestamp_ns": base_ns,
            "events": [
                {"name": "fusion.1", "offset_ps": 0,
                 "duration_ps": 60_000_000, "stats": {"hlo_op": "fusion.1"}},
                {"name": "all-reduce.3", "offset_ps": 55_000_000,
                 "duration_ps": 30_000_000},
            ],
        }],
    }]


def test_merged_timeline_clock_alignment(tmp_path):
    """Two ranks capture the same step with a 2.5 ms clock skew; their
    heartbeat step clocks carry the same skew.  After the heartbeat-derived
    offsets are applied, the merged Chrome trace must line the collectives
    up to well under the skew."""
    skew_s = 0.0025
    t0 = 1_000_000
    tl0 = timeline.parse_xspace_bytes(
        timeline.encode_xspace(_synthetic_capture(t0), hostname="host0"),
        source="rank0")
    tl1 = timeline.parse_xspace_bytes(
        timeline.encode_xspace(_synthetic_capture(t0 + int(skew_s * 1e9)),
                               hostname="host1"),
        source="rank1")

    hb = tmp_path / "hb"
    hb.mkdir()
    for pid, off in ((100, 0.0), (200, skew_s)):
        with open(hb / f"heartbeat-{pid}.jsonl", "w") as f:
            for step in range(6):
                f.write(json.dumps(
                    {"pid": pid, "step": step, "t": 1000.0 + step + off})
                    + "\n")
    offs = timeline.clock_offsets_from_heartbeats(str(hb))
    assert offs[100] == 0.0
    assert abs(offs[200] - skew_s) < 1e-9

    merged = timeline.to_chrome_trace(
        [(0, tl0), (1, tl1)], {0: offs[100], 1: offs[200]})
    coll = [e for e in merged["traceEvents"]
            if e.get("cat") == "collective"]
    assert len(coll) == 2
    ts = {e["pid"]: e["ts"] for e in coll}
    assert abs(ts[0] - ts[1]) < 1.0, ts  # µs — skew was 2500 µs
    # without offsets the skew is visible — proves alignment did the work
    raw = timeline.to_chrome_trace([(0, tl0), (1, tl1)])
    ts_raw = {e["pid"]: e["ts"] for e in raw["traceEvents"]
              if e.get("cat") == "collective"}
    assert abs(ts_raw[0] - ts_raw[1]) == pytest.approx(2500.0)


# --------------------------------------------------- CLI selftests (tier-1)

def test_obs_timeline_selftest_subprocess():
    """The decoder/analyzer CLI end to end on the checked-in fixture —
    fast (no jax import on this path)."""
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "obs_timeline.py"),
         "--selftest"],
        capture_output=True, text=True, timeout=120, cwd=ROOT)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "selftest OK" in out.stdout
