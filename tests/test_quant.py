"""Int8 weight-only serving path: quantized tree structure matches the
quant model, numerics stay close, decode stays self-consistent."""

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_tpu.models.quant import (
    QuantDense,
    quantize_kernel,
    quantize_lm_params,
)
from pytorch_distributed_tpu.models.transformer import TransformerLM

CFG = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=2)


def _params(seed=0):
    model = TransformerLM(**CFG)
    tokens = jnp.zeros((1, 16), jnp.int32)
    return model.init(jax.random.PRNGKey(seed), tokens)["params"]


def test_quant_dense_matches_dense():
    """Per-channel int8 dequant matmul tracks the fp matmul to ~1%."""
    rng = np.random.default_rng(0)
    w = rng.normal(size=(64, 48)).astype(np.float32)
    x = jnp.asarray(rng.normal(size=(4, 10, 64)).astype(np.float32))
    w_q, scale = quantize_kernel(w)

    ref = np.asarray(x) @ w
    got = QuantDense(48, use_bias=False).apply(
        {"params": {"w_q": w_q, "scale": scale}}, x)
    err = np.abs(np.asarray(got) - ref).max() / np.abs(ref).max()
    assert err < 0.02, err


def test_quantized_tree_matches_quant_model_structure():
    """quantize_lm_params output must be apply-able by the quant model:
    identical tree paths (kernel -> w_q + scale under qkv/proj/fc1/fc2,
    everything else untouched) and int8 leaves where promised."""
    params = _params()
    qparams = quantize_lm_params(params)

    qmodel = TransformerLM(**CFG, quant="int8")
    want = jax.eval_shape(
        lambda: qmodel.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 16), jnp.int32)))["params"]
    got_paths = {jax.tree_util.keystr(p): v.dtype
                 for p, v in jax.tree_util.tree_leaves_with_path(qparams)}
    want_paths = {jax.tree_util.keystr(p): v.dtype
                  for p, v in jax.tree_util.tree_leaves_with_path(want)}
    assert got_paths == want_paths
    assert any(d == jnp.int8 for d in got_paths.values())


def test_quant_logits_close_and_decode_consistent():
    """fp32 vs int8 logits stay directionally identical (cosine > 0.999),
    and the quant model's cached decode equals its own full forward —
    the KV-cache discipline is quantization-independent."""
    params = _params(seed=1)
    qparams = quantize_lm_params(params)
    rng = np.random.default_rng(1)
    tokens = jnp.asarray(rng.integers(0, 64, size=(2, 12)).astype(np.int32))

    fp = np.asarray(TransformerLM(**CFG).apply({"params": params}, tokens))
    qu = np.asarray(TransformerLM(**CFG, quant="int8").apply(
        {"params": qparams}, tokens))
    cos = (fp * qu).sum() / (np.linalg.norm(fp) * np.linalg.norm(qu))
    assert cos > 0.999, cos

    dec = TransformerLM(**CFG, quant="int8", decode=True, max_len=12)
    cache = dec.init(jax.random.PRNGKey(0), tokens)["cache"]
    out, mut = dec.apply({"params": qparams, "cache": cache},
                         tokens[:, :6], mutable=["cache"])
    parts = [out]
    cache = mut["cache"]
    for t in range(6, 12):
        out, mut = dec.apply({"params": qparams, "cache": cache},
                             tokens[:, t:t + 1], mutable=["cache"])
        parts.append(out)
        cache = mut["cache"]
    inc = np.asarray(jnp.concatenate(parts, axis=1))
    np.testing.assert_allclose(inc, qu, rtol=2e-4, atol=2e-4)


def test_quant_generate_runs_and_caches():
    """generate(quant='int8') decodes from a quantized tree; the program
    cache keys on quant so fp and int8 coexist."""
    from pytorch_distributed_tpu.models import generate as gen_mod

    params = _params(seed=2)
    qparams = quantize_lm_params(params)
    prompt = jnp.zeros((1, 4), jnp.int32)
    fp_toks = gen_mod.generate(params, prompt, 6, **CFG)
    q_toks = gen_mod.generate(qparams, prompt, 6, **CFG, quant="int8")
    assert q_toks.shape == (1, 6) and q_toks.dtype == jnp.int32
    # int8 is lossy, so exact fp equality is seed luck, not a contract.
    # The contracts: the int8 stream is deterministic, and it stays close
    # to the fp stream at init-scale weights (quant noise ~1% vs large
    # logit gaps — a fully diverged stream means a broken dequant).
    q_again = gen_mod.generate(qparams, prompt, 6, **CFG, quant="int8")
    np.testing.assert_array_equal(np.asarray(q_toks), np.asarray(q_again))
    agree = (np.asarray(q_toks) == np.asarray(fp_toks)).mean()
    assert agree >= 0.5, f"int8 stream diverged from fp: agreement {agree}"


def test_quantize_skips_moe_expert_stacks():
    """MoE expert fc1/fc2 kernels share scope names with block MLPs but
    are [E, in, out] stacks — they must stay fp, and the converted tree
    must still apply cleanly to the quant MoE model."""
    model = TransformerLM(**CFG, moe_experts=2)
    tokens = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    qparams = quantize_lm_params(params)

    moe0 = qparams["block_0"]["moe"]
    flat = jax.tree_util.tree_leaves_with_path(moe0)
    assert all(v.dtype != jnp.int8 for _, v in flat)
    # attention kernels in the same tree DID quantize
    assert qparams["block_0"]["attn"]["qkv"]["w_q"].dtype == jnp.int8

    qmodel = TransformerLM(**CFG, moe_experts=2, quant="int8")
    logits = qmodel.apply({"params": qparams}, tokens)
    assert logits.shape == (1, 8, CFG["vocab_size"])


def test_tp_generate_with_quant():
    """TP x int8: w_q shards like kernel, column-parallel scales shard on
    the output dim — the sharded quant decode reproduces the single-device
    quant stream."""
    from pytorch_distributed_tpu.models.generate import (
        generate,
        tp_generate,
    )
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
    from pytorch_distributed_tpu.parallel.tp import tp_specs

    params = _params(seed=3)
    qparams = quantize_lm_params(params)
    # every quantized leaf got a real (non-replicated) kernel spec
    from jax.sharding import PartitionSpec as P
    specs = tp_specs(qparams)
    qkv = specs["block_0"]["attn"]["qkv"]
    assert qkv["w_q"] == P(None, "model") and qkv["scale"] == P("model")
    proj = specs["block_0"]["attn"]["proj"]
    assert proj["w_q"] == P("model", None) and proj["scale"] == P()

    prompt = jnp.zeros((2, 4), jnp.int32)
    want = generate(qparams, prompt, 6, **CFG, quant="int8")
    mesh = build_mesh(MeshSpec(("model",), (4,)), jax.devices()[:4])
    got = tp_generate(qparams, prompt, 6, mesh=mesh, **CFG, quant="int8")
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
