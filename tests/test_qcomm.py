"""Quantized gradient collectives (ops/qcomm.py) + their fences.

Covers the ISSUE-8 contracts end to end on the simulated CPU mesh:

- per-block symmetric quantize/dequantize round-trip error bounds;
- error-feedback residual exactness: the residuals carried in TrainState
  telescope to exactly (true sum - wire sum), both for the emulated
  (GSPMD) path and the explicit shard_map two-hop decomposition;
- step parity: int8+EF training tracks the f32 step at loose tolerance;
- wire fence: the compiled int8 step's measured grad_sync wire bytes
  (comm ledger) shrink >= 3.5x vs the f32 explicit step, and the
  analytic model (obs/flops.py image_comm_bytes_compressed) lands within
  the +-15% residual window;
- shardlint fence: the pinned train_image_int8 collective baseline makes
  an f32 fallback (all-reduce bytes at grad size) a hard error;
- mode plumbing: resolve_mode's wire_dtype deprecation shim, the GSPMD
  numerics-emulation warning, and checkpoint round-trip of residuals.
"""

import os
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.ops import qcomm
from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
from pytorch_distributed_tpu.train.optim import sgd_init
from pytorch_distributed_tpu.train.state import TrainState
from pytorch_distributed_tpu.train.steps import make_train_step

from tests.test_steps import _MLP, _leaves_allclose, _setup_mlp


# ------------------------------------------------------------- quant kernels

def test_int8_roundtrip_error_bound():
    """|x - dq(q(x))| <= scale/2 per element (symmetric round-to-nearest)."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(1000,)).astype(np.float32) * 3.0)
    q, scale = qcomm.quantize_blockwise(x, "int8")
    assert q.dtype == jnp.int8
    dq = qcomm.dequantize_blockwise(q, scale, x.shape)
    nb = scale.size
    per_block = np.repeat(np.asarray(scale), qcomm.DEFAULT_BLOCK)[: x.size]
    np.testing.assert_array_less(
        np.abs(np.asarray(x - dq)), per_block / 2 + 1e-12)
    assert nb == int(np.ceil(x.size / qcomm.DEFAULT_BLOCK))


def test_quantize_zero_block_is_exact():
    x = jnp.zeros((512,), jnp.float32)
    q, scale = qcomm.quantize_blockwise(x, "int8")
    np.testing.assert_array_equal(np.asarray(q), 0)
    np.testing.assert_array_equal(np.asarray(scale), 0.0)
    np.testing.assert_array_equal(
        np.asarray(qcomm.dequantize_blockwise(q, scale, x.shape)), 0.0)


@pytest.mark.skipif(not qcomm.fp8_supported(), reason="no fp8 dtype")
def test_fp8_roundtrip_loose():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(512,)).astype(np.float32))
    dq = qcomm.fake_quantize(x, "fp8")
    # e4m3 carries ~3 mantissa bits; block scaling keeps it relative.
    np.testing.assert_allclose(np.asarray(dq), np.asarray(x),
                               rtol=0.13, atol=1e-3)


def test_chunk_layout_small_leaf_shrinks_block():
    # 10-element leaf, 4 ranks: chunk 3 -> block 3, padded 12 (not 1024).
    padded, nb = qcomm.chunk_layout(10, 4, 256)
    assert padded == 12 and nb == 1
    # Exact multiples pad nothing.
    padded, nb = qcomm.chunk_layout(49152, 4, 256)
    assert padded == 49152 and nb == 48


# ------------------------------------------------------------- mode plumbing

def test_resolve_mode_wire_dtype_shim():
    with pytest.deprecated_call():
        mode, cast = qcomm.resolve_mode(None, jnp.bfloat16)
    assert mode == "bf16" and cast == jnp.bfloat16
    assert qcomm.resolve_mode(None, None) == ("none", None)
    assert qcomm.resolve_mode("none", None) == ("none", None)
    mode, cast = qcomm.resolve_mode("bf16", None)
    assert mode == "bf16" and cast == jnp.bfloat16
    assert qcomm.resolve_mode("int8", None) == ("int8", None)
    with pytest.raises(ValueError):
        qcomm.resolve_mode("int4", None)
    with pytest.raises(ValueError):
        qcomm.resolve_mode("int8", jnp.bfloat16)


def test_gspmd_int8_warns_numerics_emulation():
    mesh, model, state, batch = _setup_mlp(num_devices=4)
    with pytest.warns(UserWarning, match="NUMERICS emulation"):
        make_train_step(model, mesh, grad_compress="int8")


# -------------------------------------------------------- error feedback

def test_emulated_error_feedback_telescopes():
    """compress_emulated: residual == (input - fake-quantized output)."""
    rng = np.random.default_rng(2)
    grads = {"w": jnp.asarray(rng.normal(size=(300,)).astype(np.float32))}
    residual = qcomm.init_residual(grads, "int8")
    out, res = qcomm.compress_emulated(grads, residual, "int8")
    np.testing.assert_allclose(
        np.asarray(res["w"]), np.asarray(grads["w"] - out["w"]),
        rtol=0, atol=1e-6)
    # Second step folds the carried residual into the quantizer input.
    out2, res2 = qcomm.compress_emulated(grads, res, "int8")
    np.testing.assert_allclose(
        np.asarray(res2["w"]),
        np.asarray(grads["w"] + res["w"] - out2["w"]), rtol=0, atol=1e-6)


def test_compressed_psum_exact_telescoping():
    """Explicit two-hop decomposition: summed residual slots equal the
    true f32 sum minus what crossed the wire — exactly, not approximately
    (the DynamiQ invariant the convergence claim rests on)."""
    n = 4
    mesh = build_mesh(MeshSpec(("data",), (n,)), jax.devices()[:n])
    rng = np.random.default_rng(3)
    per_rank = jnp.asarray(rng.normal(size=(n, 700)).astype(np.float32))
    res0 = jnp.zeros((n, 700), jnp.float32)

    from jax.sharding import PartitionSpec as P

    def body(x, r):
        synced, new_r = qcomm.compressed_psum(
            {"g": x[0]}, {"g": r}, "data", mode="int8")
        return synced["g"], new_r["g"]

    wire_sum, res = jax.shard_map(
        body, mesh=mesh, in_specs=(P("data"), P("data")),
        out_specs=(P(), P("data")), check_vma=False)(per_rank, res0)
    true_sum = np.asarray(per_rank).sum(axis=0)
    np.testing.assert_allclose(
        np.asarray(res).sum(axis=0),
        true_sum - np.asarray(wire_sum), rtol=0, atol=5e-5)


# ------------------------------------------------------------- step parity

def _fresh_state(variables, mode, explicit, n_data):
    v = jax.tree_util.tree_map(jnp.array, variables)
    residual = qcomm.init_residual(v["params"], mode, explicit=explicit,
                                   n_data=n_data)
    return TrainState.create(v, sgd_init(v["params"]), residual=residual)


def test_int8_step_parity_vs_f32():
    """3 explicit-collective steps: int8+EF params track f32 at loose
    tolerance, and the residual state is actually nonzero (EF is live)."""
    n = 4
    mesh = build_mesh(MeshSpec(("data",), (n,)), jax.devices()[:n])
    model = _MLP(classes=10)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8, 8, 3)))
    rng = np.random.default_rng(4)
    batches = [{
        "images": rng.normal(size=(16, 8, 8, 3)).astype(np.float32),
        "labels": rng.integers(0, 10, size=16).astype(np.int32),
        "weights": np.ones(16, np.float32),
    } for _ in range(3)]

    def run(mode):
        step = make_train_step(model, mesh, explicit_collectives=True,
                               grad_compress=mode)
        state = _fresh_state(variables, mode, True, n)
        for b in batches:
            state, metrics = step(state, b, jnp.float32(0.1))
        return state, float(metrics["loss"])

    s_f32, loss_f32 = run("none")
    s_int8, loss_int8 = run("int8")
    np.testing.assert_allclose(loss_int8, loss_f32, rtol=5e-3)
    _leaves_allclose(s_f32.params, s_int8.params, rtol=0.05, atol=5e-3)
    res_norm = sum(float(jnp.sum(jnp.abs(l)))
                   for l in jax.tree_util.tree_leaves(s_int8.residual))
    assert res_norm > 0.0


# ------------------------------------------------------------ wire fences

def _recipe_ledger(get_lowering, name):
    """Comm ledger for one shardlint recipe's cached session lowering —
    pure text parsing over the memoized compile (analysis.core), instead
    of a fresh per-test ``lower().compile()``."""
    from pytorch_distributed_tpu.obs import comms

    low = get_lowering(name)
    return comms.ledger_from_hlo_text(low.text, step=name,
                                      mesh_shape=low.mesh_shape)


def test_int8_wire_bytes_fence_and_analytic_parity(get_lowering):
    """The ISSUE-8 acceptance fence, measured from compiled HLO: int8
    grad_sync wire bytes shrink >= 3.5x vs f32, entries are labeled with
    the int8 wire encoding, and the analytic model lands within +-15%."""
    from pytorch_distributed_tpu.obs.flops import (
        comm_residual_pct,
        image_comm_bytes_compressed,
    )

    lg_f32 = _recipe_ledger(get_lowering, "train_image_explicit")
    lg_int8 = _recipe_ledger(get_lowering, "train_image_int8")

    gs_f32 = lg_f32.by_phase()["grad_sync"]
    gs_int8 = lg_int8.by_phase()["grad_sync"]
    ratio = gs_f32["wire_bytes"] / gs_int8["wire_bytes"]
    assert ratio >= 3.5, (ratio, gs_f32, gs_int8)

    encodings = lg_int8.phase_wire_encodings("grad_sync")
    assert "int8" in encodings, encodings
    # payload dominates the f32 scale side-cars
    assert encodings["int8"] > 10 * encodings.get("f32", 0.0), encodings

    # both recipes share _tiny_image_model; leaf sizes off the cached
    # lowering's own state argument
    leaf_sizes = [l.size for l in jax.tree_util.tree_leaves(
        get_lowering("train_image_int8").args[0].params)]
    pred = image_comm_bytes_compressed(leaf_sizes, dp=4, mode="int8")
    assert comm_residual_pct(
        pred.total_bytes, lg_int8.total_bytes) <= 15.0, (
        pred.total_bytes, lg_int8.total_bytes)


def test_wire_encoding_json_roundtrip(tmp_path, get_lowering):
    """Ledger JSON round-trips wire_encoding; legacy entries without the
    field load with the f32 default."""
    from pytorch_distributed_tpu.obs import comms

    lg = _recipe_ledger(get_lowering, "train_image_int8")
    path = os.path.join(tmp_path, "comm_ledger.json")
    comms.write_ledgers(path, [lg])
    loaded = comms.load_ledgers(path)["train_image_int8"]
    assert (loaded.phase_wire_encodings("grad_sync")
            == lg.phase_wire_encodings("grad_sync"))

    # legacy payload: entries with no wire_encoding key
    import json

    data = json.load(open(path))
    for e in data["train_image_int8"]["entries"]:
        e.pop("wire_encoding")
    with open(path, "w") as f:
        json.dump(data, f)
    legacy = comms.load_ledgers(path)["train_image_int8"]
    assert {e.wire_encoding for e in legacy.entries} == {"f32"}


def test_shardlint_baseline_fences_f32_fallback(get_lowering):
    """The pinned train_image_int8 budget has no room for an f32 gradient
    all-reduce: a fallback shows up as error-severity
    collective-regression findings on both the kind and the total."""
    from pytorch_distributed_tpu.analysis import core
    from pytorch_distributed_tpu.analysis.report import (
        baseline_entry,
        diff_against_baseline,
        load_baseline,
    )

    base = load_baseline(core.baseline_path())
    assert "train_image_int8" in base and "train_image_bf16" in base
    entry = base["train_image_int8"]
    # the pinned budget's all-reduce line is scalars-only (16 B), so any
    # f32 gradient fallback necessarily exceeds it
    assert entry["collectives"]["all-reduce"]["bytes"] < 100
    assert entry["collectives"]["all-to-all"]["bytes"] > 1000

    rep = core.analyze_lowering(get_lowering("train_image_int8"))
    assert diff_against_baseline(rep, entry) == []

    # simulate the fallback: gradient bytes land on all-reduce again
    fallback = core.analyze_lowering(get_lowering("train_image_explicit"))
    fallback.name = "train_image_int8"
    regress = diff_against_baseline(fallback, entry)
    errors = [f for f in regress if f.severity == "error"]
    assert any(f.where.endswith(":all-reduce") for f in errors), regress
    assert any(f.where.endswith(":total") for f in errors), regress
    # sanity: the real lowering regenerates its own pinned entry
    assert baseline_entry(rep) == entry


# ------------------------------------------------------------- checkpoints

def test_checkpoint_residual_roundtrip(tmp_path):
    from pytorch_distributed_tpu.train.checkpoint import (
        load_checkpoint,
        save_checkpoint,
    )

    model = _MLP(classes=4)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8, 8, 3)))
    residual = qcomm.init_residual(variables["params"], "int8",
                                   explicit=True, n_data=4)
    residual = jax.tree_util.tree_map(
        lambda r: r + 0.25, residual)  # nonzero, so the restore is visible
    state = TrainState.create(variables, sgd_init(variables["params"]),
                              residual=residual)
    path = save_checkpoint(str(tmp_path), state, 0, "mlp", 0.0, False,
                           ft={"step": 3})

    template = TrainState.create(
        jax.tree_util.tree_map(jnp.zeros_like, variables),
        sgd_init(variables["params"]),
        residual=qcomm.init_residual(variables["params"], "int8",
                                     explicit=True, n_data=4))
    loaded, meta = load_checkpoint(path, template)
    _leaves_allclose(loaded.residual, state.residual, rtol=0, atol=0)
    assert meta["ft"]["step"] == 3

    # mode switch: an f32 template (no residual) loads the same payload
    plain = TrainState.create(
        jax.tree_util.tree_map(jnp.zeros_like, variables),
        sgd_init(variables["params"]))
    loaded2, _ = load_checkpoint(path, plain)
    assert jax.tree_util.tree_leaves(loaded2.residual) == []
    _leaves_allclose(loaded2.params, state.params, rtol=0, atol=0)


def test_checkpoint_legacy_payload_zero_residual(tmp_path):
    """A checkpoint written WITHOUT residuals restores into a quantized
    template with zero residuals (EF restarts cleanly on mode switch)."""
    from pytorch_distributed_tpu.train.checkpoint import (
        load_checkpoint,
        save_checkpoint,
    )

    model = _MLP(classes=4)
    variables = model.init(jax.random.PRNGKey(1), jnp.zeros((1, 8, 8, 3)))
    state = TrainState.create(variables, sgd_init(variables["params"]))
    path = save_checkpoint(str(tmp_path), state, 0, "mlp", 0.0, False)

    template = TrainState.create(
        jax.tree_util.tree_map(jnp.zeros_like, variables),
        sgd_init(variables["params"]),
        residual=qcomm.init_residual(variables["params"], "int8",
                                     explicit=True, n_data=4))
    loaded, _ = load_checkpoint(path, template)
    for leaf in jax.tree_util.tree_leaves(loaded.residual):
        np.testing.assert_array_equal(np.asarray(leaf), 0.0)
    _leaves_allclose(loaded.params, state.params, rtol=0, atol=0)
