"""u8_host (native C++ normalize) and u8_wire (device normalize) pipelines
produce the same normalized batches as the f32 reference pipeline, and train
end-to-end through the Trainer on an ImageFolder tree."""

import numpy as np
import pytest

from pytorch_distributed_tpu.data import (
    DataLoader,
    DeviceFeeder,
    DistributedShardSampler,
    SyntheticImageDataset,
)
from pytorch_distributed_tpu.data.transforms import (
    IMAGENET_MEAN,
    IMAGENET_STD,
    eval_transform,
    eval_transform_u8,
)
from pytorch_distributed_tpu.parallel import data_parallel_mesh
from pytorch_distributed_tpu.train.config import Config
from pytorch_distributed_tpu.train.trainer import Trainer


def _loaders(n=16, bsz=8, size=16):
    """Same dataset through the f32 eval stack and the u8 eval stack.
    (Eval stacks are deterministic, so outputs must match exactly.)"""
    common = dict(length=n, num_classes=4, image_size=32, seed=0)
    ds_f32 = SyntheticImageDataset(transform=eval_transform(size, resize=size), **common)
    ds_u8 = SyntheticImageDataset(transform=eval_transform_u8(size, resize=size), **common)
    mk = lambda ds, mode: DataLoader(
        ds, bsz, sampler=DistributedShardSampler(n, shuffle=False), batch_mode=mode
    )
    return mk(ds_f32, "f32"), mk(ds_u8, "u8_host"), mk(ds_u8, "u8_wire")


def test_u8_host_matches_f32_pipeline():
    f32, u8h, _ = _loaders()
    for a, b in zip(iter(f32), iter(u8h)):
        assert b["images"].dtype == np.float32
        np.testing.assert_allclose(a["images"], b["images"], rtol=1e-5, atol=1e-5)
        np.testing.assert_array_equal(a["labels"], b["labels"])


def test_u8_wire_normalizes_on_device():
    f32, _, u8w = _loaders()
    feeder = DeviceFeeder(data_parallel_mesh())
    host = next(iter(u8w))
    assert host["images"].dtype == np.uint8  # uint8 crosses the wire
    dev = next(iter(feeder(iter(u8w))))
    ref = next(iter(f32))
    assert str(dev["images"].dtype) == "float32"
    np.testing.assert_allclose(
        np.asarray(dev["images"]), ref["images"], rtol=1e-5, atol=1e-5
    )


def test_trainer_u8host_on_imagefolder(tmp_path):
    from PIL import Image

    rng = np.random.default_rng(0)
    for split in ("train", "val"):
        for c in range(2):
            d = tmp_path / "data" / split / f"c{c}"
            d.mkdir(parents=True)
            for i in range(8):
                Image.fromarray(
                    rng.integers(0, 256, size=(40, 40, 3)).astype(np.uint8)
                ).save(d / f"{i}.png")
    for wire in ("u8host", "u8"):
        cfg = Config(
            arch="resnet18", batch_size=8, epochs=1, print_freq=1, seed=0,
            data=str(tmp_path / "data"), image_size=32, wire=wire,
            checkpoint_dir=str(tmp_path / f"ckpt_{wire}"), workers=2,
        )
        best = Trainer(cfg).fit()
        assert 0.0 <= best <= 100.0
        assert (tmp_path / f"ckpt_{wire}" / "checkpoint.msgpack").exists()
