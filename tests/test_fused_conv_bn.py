"""Parity tests for the fused conv1x1+BN backward (ops/fused_conv_bn.py).

The oracle is the PURE-autodiff composition (plain jnp conv + batch-norm
math, no custom VJP anywhere), so these tests validate the whole BN-dx fold
— the per-channel dy algebra AND the Pallas dgrad/wgrad kernel — not just
consistency with ops/fused_bn's hand-written backward.

Kernel runs in Pallas interpret mode on CPU (exact math, slow), the same
code path compiled on TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.ops.fused_conv_bn import (
    conv1x1_bn_act,
    conv3x3_bn_act,
    _fused_dgrad_wgrad,
)

EPS = 1e-5


def _ref(a, w, gamma, beta, relu):
    """Pure-jnp conv + BN(+ReLU), f32 stats — autodiff provides the oracle
    backward.  Variance uses the same one-pass clamped formula as _stats.
    Kernel spatial shape selects 1x1 VALID vs 3x3 stride-1 SAME."""
    pad = "VALID" if w.shape[:2] == (1, 1) else ((1, 1), (1, 1))
    y = jax.lax.conv_general_dilated(
        a, w.astype(a.dtype), (1, 1), pad,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    yf = y.astype(jnp.float32)
    axes = tuple(range(y.ndim - 1))
    mu = yf.mean(axes)
    var = jnp.maximum((yf * yf).mean(axes) - mu * mu, 0.0)
    inv = jax.lax.rsqrt(var + EPS)
    o = ((yf - mu) * inv * gamma + beta).astype(y.dtype)
    return jax.nn.relu(o) if relu else o


def _rand(key, *shape, dtype=jnp.float32):
    return jax.random.normal(key, shape, jnp.float32).astype(dtype)


@pytest.mark.parametrize("relu", [True, False])
def test_op_parity_f32(relu):
    k = jax.random.split(jax.random.PRNGKey(0), 5)
    a = _rand(k[0], 2, 5, 5, 24)          # M = 50 -> exercises the pad path
    w = _rand(k[1], 1, 1, 24, 16)
    gamma = _rand(k[2], 16) * 0.5 + 1.0
    beta = _rand(k[3], 16) * 0.1
    cot = _rand(k[4], 2, 5, 5, 16)

    def fused_loss(a, w, g, b):
        o, _, _ = conv1x1_bn_act(a, w, g, b, EPS, relu, True)
        return jnp.sum(o * cot)

    def ref_loss(a, w, g, b):
        return jnp.sum(_ref(a, w, g, b, relu) * cot)

    fo = fused_loss(a, w, gamma, beta)
    ro = ref_loss(a, w, gamma, beta)
    np.testing.assert_allclose(fo, ro, rtol=1e-5)

    fg = jax.grad(fused_loss, argnums=(0, 1, 2, 3))(a, w, gamma, beta)
    rg = jax.grad(ref_loss, argnums=(0, 1, 2, 3))(a, w, gamma, beta)
    for f, r, name in zip(fg, rg, ("da", "dw", "dgamma", "dbeta")):
        np.testing.assert_allclose(f, r, rtol=2e-4, atol=2e-5, err_msg=name)


@pytest.mark.parametrize("relu", [True, False])
def test_op_parity_3x3_f32(relu):
    """The 3x3 stride-1 fold (whole-plane per-image tiling): N=3 images
    exercises the dW accumulation across the grid; 5x6 spatial exercises
    non-square planes and the zero-pad taps."""
    k = jax.random.split(jax.random.PRNGKey(4), 5)
    a = _rand(k[0], 3, 5, 6, 8)
    w = _rand(k[1], 3, 3, 8, 16) * 0.4
    gamma = _rand(k[2], 16) * 0.5 + 1.0
    beta = _rand(k[3], 16) * 0.1
    cot = _rand(k[4], 3, 5, 6, 16)

    def fused_loss(a, w, g, b):
        o, _, _ = conv3x3_bn_act(a, w, g, b, EPS, relu, True)
        return jnp.sum(o * cot)

    def ref_loss(a, w, g, b):
        return jnp.sum(_ref(a, w, g, b, relu) * cot)

    np.testing.assert_allclose(fused_loss(a, w, gamma, beta),
                               ref_loss(a, w, gamma, beta), rtol=1e-5)
    fg = jax.grad(fused_loss, argnums=(0, 1, 2, 3))(a, w, gamma, beta)
    rg = jax.grad(ref_loss, argnums=(0, 1, 2, 3))(a, w, gamma, beta)
    for f, r, name in zip(fg, rg, ("da", "dw", "dgamma", "dbeta")):
        np.testing.assert_allclose(f, r, rtol=3e-4, atol=3e-5, err_msg=name)


@pytest.mark.parametrize("ksz,op", [((1, 1), conv1x1_bn_act),
                                    ((3, 3), conv3x3_bn_act)])
def test_op_parity_bf16_inputs(ksz, op):
    """bf16 activations (the bench policy) for BOTH kernel shapes: the
    fused matmuls run in bf16 with f32 accumulation, like XLA's conv
    backward — looser tolerance."""
    k = jax.random.split(jax.random.PRNGKey(1), 5)
    a = _rand(k[0], 4, 4, 4, 32, dtype=jnp.bfloat16)
    w = _rand(k[1], *ksz, 32, 16) * (0.4 if ksz == (3, 3) else 1.0)
    gamma = _rand(k[2], 16) * 0.5 + 1.0
    beta = _rand(k[3], 16) * 0.1
    cot = _rand(k[4], 4, 4, 4, 16)

    def fused_loss(a, w, g, b):
        o, _, _ = op(a, w, g, b, EPS, True, True)
        return jnp.sum(o.astype(jnp.float32) * cot)

    def ref_loss(a, w, g, b):
        return jnp.sum(_ref(a, w, g, b, True).astype(jnp.float32) * cot)

    fg = jax.grad(fused_loss, argnums=(1, 2, 3))(a, w, gamma, beta)
    rg = jax.grad(ref_loss, argnums=(1, 2, 3))(a, w, gamma, beta)
    for f, r, name in zip(fg, rg, ("dw", "dgamma", "dbeta")):
        np.testing.assert_allclose(f, r, rtol=0.05, atol=0.05, err_msg=name)


def test_vmem_guard_declines_oversized_planes():
    from pytorch_distributed_tpu.ops.fused_conv_bn import (
        conv3x3_plane_fits_vmem,
    )

    # Every ResNet-50 bf16 3x3 plane fits under the raised (96 MiB)
    # Mosaic cap — including the 512-wide 7x7 stage, whose full-model
    # compile was validated on a real v5e (2026-07-31) ...
    for h, ci, co in ((56, 64, 64), (28, 128, 128), (14, 256, 256),
                      (7, 512, 512)):
        assert conv3x3_plane_fits_vmem(h, h, ci, co, 2), (h, ci, co)
    # ... while genuinely oversized working sets still decline to the
    # XLA backward (stage-1-sized planes at 256+ f32 channels).
    assert not conv3x3_plane_fits_vmem(112, 112, 256, 256, 4)
    assert not conv3x3_plane_fits_vmem(56, 56, 512, 512, 4)


def test_kernel_accumulates_across_tiles():
    """dW accumulation across >1 grid step (M spans multiple tiles)."""
    from pytorch_distributed_tpu.ops.fused_conv_bn import _pick_mtile

    k = jax.random.split(jax.random.PRNGKey(2), 3)
    M, Ci, Co = 10_000, 8, 8
    # The adaptive tile must leave >1 grid step or this test is vacuous.
    mt = _pick_mtile(M, Ci, Co, 4)
    assert M > mt, (M, mt)
    y = _rand(k[0], M, Co)
    do = _rand(k[1], M, Co)
    a = _rand(k[2], M, Ci)
    w = jnp.eye(Ci, Co)
    s = jnp.ones(Co)
    t = jnp.zeros(Co)
    u = jnp.zeros(Co)
    v = jnp.zeros(Co)
    # With s=1, t=u=0, relu off: dy == do, so dW = aT @ do, da = do @ wT.
    # f32 tolerance scales with the M-length contraction (summation-order
    # drift vs numpy), not with the default 1e-7.
    da, dw = _fused_dgrad_wgrad(y, do, a, w, s, t, u, v, False, True)
    np.testing.assert_allclose(dw, a.T @ do, rtol=1e-4, atol=1e-3)
    np.testing.assert_allclose(da, do @ w.T, rtol=1e-5, atol=1e-5)


def _tiny_resnet(fused, nc=7, block="bottleneck"):
    from pytorch_distributed_tpu.models.resnet import (
        BasicBlock,
        Bottleneck,
        ResNet,
    )

    cls = Bottleneck if block == "bottleneck" else BasicBlock
    return ResNet(stage_sizes=[1, 1], block_cls=cls, num_classes=nc,
                  num_filters=16, fused_convbn=fused)


@pytest.mark.parametrize("block", ["bottleneck", "basic"])
def test_model_tree_and_forward_parity(block):
    """Toggling fused_convbn changes NEITHER the param tree nor the forward
    numbers — the checkpoint-interchange guarantee (both block families:
    Bottleneck folds 1x1s + the stride-1 3x3; BasicBlock its 3x3 mains)."""
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 16, 16, 3))
    m0 = _tiny_resnet(False, block=block)
    m1 = _tiny_resnet(True, block=block)
    v0 = m0.init(jax.random.PRNGKey(7), x, train=False)
    v1 = m1.init(jax.random.PRNGKey(7), x, train=False)
    assert (jax.tree_util.tree_structure(v0)
            == jax.tree_util.tree_structure(v1))
    for p0, p1 in zip(jax.tree_util.tree_leaves(v0),
                      jax.tree_util.tree_leaves(v1)):
        np.testing.assert_array_equal(p0, p1)
    o0, s0 = m0.apply(v0, x, train=True, mutable=["batch_stats"])
    o1, s1 = m1.apply(v1, x, train=True, mutable=["batch_stats"])
    np.testing.assert_allclose(o0, o1, rtol=1e-5, atol=1e-5)
    for a_, b_ in zip(jax.tree_util.tree_leaves(s0),
                      jax.tree_util.tree_leaves(s1)):
        np.testing.assert_allclose(a_, b_, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("block", ["bottleneck", "basic"])
def test_model_grad_parity(block):
    """Full-model gradients agree between the fused and unfused backward."""
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 8, 8, 3))
    labels = jnp.array([0, 1, 2, 3])
    m0 = _tiny_resnet(False, block=block)
    m1 = _tiny_resnet(True, block=block)
    v = m0.init(jax.random.PRNGKey(7), x, train=False)

    def loss(m):
        def f(params):
            logits, _ = m.apply(
                {"params": params, "batch_stats": v["batch_stats"]},
                x, train=True, mutable=["batch_stats"])
            lp = jax.nn.log_softmax(logits)
            return -jnp.mean(lp[jnp.arange(4), labels])
        return f

    g0 = jax.grad(loss(m0))(v["params"])
    g1 = jax.grad(loss(m1))(v["params"])
    flat0 = jax.tree_util.tree_leaves_with_path(g0)
    flat1 = jax.tree_util.tree_leaves(g1)
    for (path, l0), l1 in zip(flat0, flat1):
        np.testing.assert_allclose(
            l0, l1, rtol=5e-4, atol=1e-5,
            err_msg=jax.tree_util.keystr(path))


def test_explicit_collectives_step_fused_parity():
    """The shard_map / explicit-collectives train step — the recommended
    multi-chip path for --fused-convbn, where the kernels see LOCAL shards
    natively — produces the same 2-step loss trajectory fused vs unfused
    (per-shard BN semantics on both sides)."""
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState
    from pytorch_distributed_tpu.train.steps import make_train_step

    mesh = build_mesh(MeshSpec(("data",), (8,)), jax.devices()[:8])
    rng = np.random.default_rng(5)
    batch = {
        "images": jnp.asarray(
            rng.normal(size=(16, 8, 8, 3)).astype(np.float32)),
        "labels": jnp.asarray(rng.integers(0, 7, size=16).astype(np.int32)),
        "weights": jnp.ones(16, jnp.float32),
    }
    x0 = jnp.zeros((1, 8, 8, 3))

    def two_step(fused):
        m = _tiny_resnet(fused)
        v = m.init(jax.random.PRNGKey(7), x0, train=False)
        state = TrainState.create(v, sgd_init(v["params"]))
        step = make_train_step(m, mesh, explicit_collectives=True)
        state, _ = step(state, batch, jnp.float32(0.1))
        _, metrics = step(state, batch, jnp.float32(0.1))
        return float(metrics["loss"])

    np.testing.assert_allclose(two_step(False), two_step(True),
                               rtol=2e-4, atol=1e-5)


@pytest.mark.parametrize("ksz,op", [((1, 1), conv1x1_bn_act),
                                    ((3, 3), conv3x3_bn_act)])
def test_gspmd_sharded_batch_parity(ksz, op):
    """The fused ops inside a GSPMD-jitted, data-sharded step: compile and
    match the unsharded result (single-program semantics are what the
    bench's 1-chip GSPMD step uses; multi-chip prefers the shard_map /
    explicit-collectives recipe where the kernels see local shards).  The
    3x3 case matters specifically because its pallas grid runs per-image
    over the very axis GSPMD shards."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = np.array(jax.devices()[:8]).reshape(8)
    mesh = Mesh(devs, ("data",))
    k = jax.random.split(jax.random.PRNGKey(3), 5)
    a = _rand(k[0], 16, 4, 4, 8)
    w = _rand(k[1], *ksz, 8, 8) * (0.4 if ksz == (3, 3) else 1.0)
    gamma = jnp.ones(8)
    beta = jnp.zeros(8)
    cot = _rand(k[4], 16, 4, 4, 8)

    def loss(a, w, g, b):
        o, _, _ = op(a, w, g, b, EPS, True, True)
        return jnp.sum(o * cot)

    grads = jax.grad(loss, argnums=(0, 1))(a, w, gamma, beta)
    sharded = NamedSharding(mesh, P("data"))
    rep = NamedSharding(mesh, P())
    jg = jax.jit(jax.grad(loss, argnums=(0, 1)),
                 in_shardings=(sharded, rep, rep, rep))(a, w, gamma, beta)
    for g_ref, g_sh in zip(grads, jg):
        np.testing.assert_allclose(g_ref, np.asarray(g_sh),
                                   rtol=1e-4, atol=1e-5)
