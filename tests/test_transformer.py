"""TransformerLM: ring-parallel model ≡ dense model, and it learns."""

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_tpu.models.transformer import TransformerLM
from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
from pytorch_distributed_tpu.ops import cross_entropy


def _mesh():
    return build_mesh(MeshSpec(("data", "seq"), (2, 4)), jax.devices()[:8])


def _tokens(B=2, L=32, vocab=64, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, vocab, size=(B, L)).astype(np.int32))


def test_ring_model_matches_dense_model():
    mesh = _mesh()
    kw = dict(vocab_size=64, d_model=64, n_heads=4, n_layers=2)
    dense = TransformerLM(**kw)
    ringm = TransformerLM(**kw, mesh=mesh, ring=True)
    tokens = _tokens()
    params = dense.init(jax.random.PRNGKey(0), tokens)
    out_d = dense.apply(params, tokens)
    out_r = ringm.apply(params, tokens)  # same params, sp execution
    np.testing.assert_allclose(
        np.asarray(out_r), np.asarray(out_d), rtol=2e-4, atol=2e-4
    )


def test_lm_train_step_learns_over_data_seq_mesh():
    """Full sp+dp LM training step: loss must drop on a memorizable batch."""
    mesh = _mesh()
    model = TransformerLM(vocab_size=32, d_model=32, n_heads=2, n_layers=1,
                          mesh=mesh, ring=True)
    tokens = _tokens(B=4, L=16, vocab=32)
    params = model.init(jax.random.PRNGKey(0), tokens)

    def loss_fn(params, tokens):
        logits = model.apply(params, tokens)
        return cross_entropy(
            logits[:, :-1].reshape(-1, 32), tokens[:, 1:].reshape(-1)
        )

    @jax.jit
    def step(params, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        params = jax.tree_util.tree_map(lambda p, g: p - 0.5 * g, params, grads)
        return params, loss

    losses = []
    for _ in range(10):
        params, loss = step(params, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, losses
