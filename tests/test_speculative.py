"""Speculative decoding: greedy stream EXACTLY equals the target's own,
the acceptance math preserves the target distribution, self-draft accepts
everything."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.models.generate import greedy_generate
from pytorch_distributed_tpu.models.speculative import (
    _accept,
    _dist,
    _resample,
    speculative_generate,
)
from pytorch_distributed_tpu.models.transformer import TransformerLM

TARGET = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=2)
DRAFT = dict(vocab_size=64, d_model=16, n_heads=2, n_layers=1)


def _init(cfg, seed):
    model = TransformerLM(**cfg)
    return model.init(jax.random.PRNGKey(seed),
                      jnp.zeros((1, 16), jnp.int32))["params"]


@pytest.mark.parametrize("gamma", [1, 3, 4])
def test_greedy_equals_target_stream(gamma):
    """Temperature 0: the speculative output must be the target model's
    greedy stream token-for-token, whatever the draft proposes."""
    tp, dp = _init(TARGET, 0), _init(DRAFT, 7)
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, 64, size=(1, 6)).astype(np.int32))
    n_new = 12

    want = np.asarray(greedy_generate(tp, prompt, n_new, **TARGET))
    got, stats = speculative_generate(
        tp, dp, prompt, n_new, target_cfg=TARGET, draft_cfg=DRAFT,
        gamma=gamma)
    np.testing.assert_array_equal(np.asarray(got), want)
    assert stats["tokens"] == n_new
    assert stats["target_passes"] >= 1


def test_self_draft_accepts_everything():
    """Draft == target (greedy): every proposal is accepted, so each
    target pass yields gamma+1 tokens and the stream is still exact."""
    tp = _init(TARGET, 1)
    prompt = jnp.zeros((1, 4), jnp.int32)
    n_new = 13
    gamma = 4

    want = np.asarray(greedy_generate(tp, prompt, n_new, **TARGET))
    got, stats = speculative_generate(
        tp, tp, prompt, n_new, target_cfg=TARGET, draft_cfg=TARGET,
        gamma=gamma)
    np.testing.assert_array_equal(np.asarray(got), want)
    # full rounds accept all gamma proposals
    assert stats["mean_accepted"] == pytest.approx(gamma, abs=1.0)
    assert stats["tokens_per_target_pass"] > 2.0


def test_sampled_mode_runs_and_is_reproducible():
    tp, dp = _init(TARGET, 2), _init(DRAFT, 3)
    prompt = jnp.zeros((1, 4), jnp.int32)
    a, sa = speculative_generate(
        tp, dp, prompt, 10, target_cfg=TARGET, draft_cfg=DRAFT, gamma=3,
        temperature=1.2, top_k=20, top_p=0.95, seed=5)
    b, _ = speculative_generate(
        tp, dp, prompt, 10, target_cfg=TARGET, draft_cfg=DRAFT, gamma=3,
        temperature=1.2, top_k=20, top_p=0.95, seed=5)
    c, _ = speculative_generate(
        tp, dp, prompt, 10, target_cfg=TARGET, draft_cfg=DRAFT, gamma=3,
        temperature=1.2, top_k=20, top_p=0.95, seed=6)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert (np.asarray(a) != np.asarray(c)).any()
    assert np.asarray(a).min() >= 0 and np.asarray(a).max() < 64
    assert sa["tokens"] == 10


def test_dist_sums_within_choice_tolerance_at_large_vocab():
    """Regression: f32-accumulated softmax sums deviate up to ~1.3e-7 at
    vocab 32k — past numpy Generator.choice's ~1.5e-8 tolerance.  _dist
    must renormalize so every vector it returns is choice-safe."""
    rng = np.random.default_rng(0)
    for seed in range(4):
        logits = np.asarray(
            np.random.default_rng(seed).normal(0, 4, size=32_768), np.float32)
        p = _dist(logits, temperature=1.1, top_k=0, top_p=0.0)
        assert abs(p.sum() - 1.0) <= 1e-12
        # the actual contract: choice must not raise
        rng.choice(len(p), p=p)
        # filtered variants too (top-k/top-p change the support)
        pk = _dist(logits, temperature=0.8, top_k=50, top_p=0.9)
        assert abs(pk.sum() - 1.0) <= 1e-12
        rng.choice(len(pk), p=pk)


@pytest.mark.parametrize("seed", [5, 11, 17])
def test_sampled_mode_multiseed(seed):
    """Sampled-mode speculative decode runs (no sum-to-1 crash) and is
    seed-reproducible across several seeds."""
    tp, dp = _init(TARGET, 2), _init(DRAFT, 3)
    prompt = jnp.zeros((1, 4), jnp.int32)
    a, sa = speculative_generate(
        tp, dp, prompt, 8, target_cfg=TARGET, draft_cfg=DRAFT, gamma=2,
        temperature=1.0, top_k=0, top_p=0.9, seed=seed)
    b, _ = speculative_generate(
        tp, dp, prompt, 8, target_cfg=TARGET, draft_cfg=DRAFT, gamma=2,
        temperature=1.0, top_k=0, top_p=0.9, seed=seed)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert sa["tokens"] == 8


def test_speculative_int8_target():
    """lm_generate --quant int8 --spec-draft composition: speculative
    greedy with a quantized target equals the int8 target's own greedy
    stream (quantization-consistent oracle)."""
    from pytorch_distributed_tpu.models.quant import quantize_lm_params

    tp, dp = _init(TARGET, 4), _init(DRAFT, 5)
    qtp = quantize_lm_params(tp)
    prompt = jnp.zeros((1, 5), jnp.int32)
    n_new = 9
    want = np.asarray(greedy_generate(
        qtp, prompt, n_new, **TARGET, quant="int8"))
    got, stats = speculative_generate(
        qtp, dp, prompt, n_new, target_cfg=TARGET, draft_cfg=DRAFT,
        gamma=3, quant="int8")
    np.testing.assert_array_equal(np.asarray(got), want)
    assert stats["tokens"] == n_new


def test_acceptance_math_preserves_target_distribution():
    """The Leviathan identity, verified empirically on crafted p/q:
    accept-or-resample must produce samples distributed as p."""
    p = np.array([0.5, 0.3, 0.15, 0.05])
    q = np.array([0.1, 0.6, 0.1, 0.2])
    rng = np.random.default_rng(0)
    n = 60_000
    counts = np.zeros(4)
    for _ in range(n):
        x = int(rng.choice(4, p=q))  # draft proposes from q
        if _accept(p, q, x, rng, greedy=False):
            counts[x] += 1
        else:
            counts[_resample(p, q, rng, greedy=False)] += 1
    emp = counts / n
    np.testing.assert_allclose(emp, p, atol=0.01)


def test_acceptance_math_degenerate_equal_dists():
    """p == q: everything accepts (ratio 1), and the residual fallback
    still samples from p instead of crashing on the 0/0 residual."""
    p = np.array([0.25, 0.25, 0.25, 0.25])
    rng = np.random.default_rng(1)
    assert all(_accept(p, p, x, rng, greedy=False) for x in range(4))
    tok = _resample(p, p, rng, greedy=False)
    assert 0 <= tok < 4
