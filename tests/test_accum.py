"""Gradient accumulation: in-graph microbatch scan ≡ single big batch."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
from pytorch_distributed_tpu.train.optim import sgd_init
from pytorch_distributed_tpu.train.state import TrainState
from pytorch_distributed_tpu.train.steps import make_train_step
from tests.test_steps import _MLP  # BN-free: accumulation is exactly equal


def _setup(batch=32, image=8, classes=10, seed=0):
    mesh = build_mesh(MeshSpec(("data",), (8,)), jax.devices()[:8])
    model = _MLP(classes=classes)
    variables = model.init(jax.random.PRNGKey(seed), jnp.zeros((1, image, image, 3)))
    rng = np.random.default_rng(seed)
    batch_data = {
        "images": rng.normal(size=(batch, image, image, 3)).astype(np.float32),
        "labels": rng.integers(0, classes, size=batch).astype(np.int32),
        "weights": np.ones(batch, np.float32),
    }
    return mesh, model, variables, batch_data


@pytest.mark.parametrize("explicit", [False, True],
                         ids=["gspmd", "explicit_collectives"])
@pytest.mark.parametrize("accum", [2, 4])
def test_accumulated_step_matches_single_batch(accum, explicit):
    """Both gradient-sync formulations: accumulation ≡ one big batch.

    In the explicit (shard_map) formulation the microbatch scan runs on the
    per-shard slice and the psum still fires once per optimizer step — the
    collective count is unchanged by accumulation."""
    mesh, model, variables, batch = _setup()
    # Copy before the donating first step consumes `variables`' buffers.
    fresh = jax.tree_util.tree_map(jnp.array, variables)
    s0 = TrainState.create(variables, sgd_init(variables["params"]))
    step1 = make_train_step(model, mesh, explicit_collectives=explicit)
    s1, m1 = step1(s0, batch, jnp.float32(0.1))

    sA = TrainState.create(fresh, sgd_init(fresh["params"]))
    stepA = make_train_step(model, mesh, explicit_collectives=explicit,
                            accum_steps=accum)
    sA1, mA = stepA(sA, batch, jnp.float32(0.1))

    np.testing.assert_allclose(float(m1["loss"]), float(mA["loss"]), rtol=1e-5)
    np.testing.assert_allclose(float(m1["acc1"]), float(mA["acc1"]), atol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(s1.params),
                    jax.tree_util.tree_leaves(sA1.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_trainer_accum_flag(tmp_path):
    from pytorch_distributed_tpu.train.config import Config
    from pytorch_distributed_tpu.train.trainer import Trainer

    cfg = Config(
        arch="resnet18", batch_size=16, epochs=1, print_freq=1, seed=0,
        synthetic=True, synthetic_length=32, image_size=32, num_classes=2,
        checkpoint_dir=str(tmp_path), workers=2, accum_steps=2,
    )
    best = Trainer(cfg).fit()
    assert 0.0 <= best <= 100.0

    with pytest.raises(ValueError, match="whole multiple"):
        Trainer(Config(
            arch="resnet18", batch_size=16, epochs=1, seed=0, synthetic=True,
            synthetic_length=32, image_size=32, num_classes=2,
            checkpoint_dir=str(tmp_path), accum_steps=3,
        ))
