"""Unified observability layer (obs/): MetricsLogger JSONL schema + lazy
device-scalar conversion, sink registration, heartbeat straggler flagging
(including across live processes), telemetry peak isolation, profiler
windows, per-stage trace annotations in a real XPlane capture, in-graph
grad-norm vs an eager recomputation, the obs_report selftest, and the
recipe --metrics-jsonl flag lint."""

import glob
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --------------------------------------------------------------- MetricsLogger
def test_metrics_logger_schema_roundtrip(tmp_path):
    from pytorch_distributed_tpu.obs import (
        REQUIRED_FIELDS,
        MetricsLogger,
        read_metrics,
    )

    path = str(tmp_path / "m.jsonl")
    with MetricsLogger(path, process_index=3, flush_every=4) as log:
        for i in range(10):
            log.log_step(i, step_time=0.010 + 0.001 * i, n_items=64,
                         lr=0.1, scalars={"loss": np.float32(2.0 - 0.1 * i)})
    recs = read_metrics(path)
    assert len(recs) == 10
    for r in recs:
        for k in REQUIRED_FIELDS:
            assert k in r, (k, r)
        assert r["process"] == 3
        assert r["step_time_p50"] <= r["step_time_p95"] <= r["step_time_max"]
        assert r["throughput"] == pytest.approx(64 / r["step_time"])
        assert isinstance(r["loss"], float)  # converted at flush
    assert [r["step"] for r in recs] == list(range(10))
    # EMA starts at the first sample and tracks the drift upward
    assert recs[0]["step_time_ema"] == pytest.approx(0.010)
    assert recs[-1]["step_time_ema"] > recs[0]["step_time_ema"]


def test_metrics_logger_lazy_conversion(tmp_path):
    """Device scalars must NOT be converted (host-synced) at log time —
    only at flush, amortized over flush_every steps (meters.py discipline)."""
    from pytorch_distributed_tpu.obs import MetricsLogger

    class LazyScalar:
        calls = 0

        def __float__(self):
            LazyScalar.calls += 1
            return 1.25

    log = MetricsLogger(str(tmp_path / "m.jsonl"), flush_every=100)
    log.log_step(0, step_time=0.01, scalars={"loss": LazyScalar()})
    log.log_step(1, step_time=0.01, scalars={"loss": LazyScalar()})
    assert LazyScalar.calls == 0  # no premature host sync
    log.flush()
    assert LazyScalar.calls == 2
    log.close()


def test_metrics_logger_sink_registration(tmp_path):
    """The three sink shapes: start/stop (telemetry), epoch_start/epoch_end
    (epoch CSV), and per-record callables — one observability entry point."""
    from pytorch_distributed_tpu.obs import MetricsLogger
    from pytorch_distributed_tpu.utils.csvlog import EpochCSVLogger

    class FakeSampler:
        running = False

        def start(self):
            self.running = True
            return self

        def stop(self):
            self.running = False

    got = []
    csv_path = str(tmp_path / "epoch.csv")
    log = MetricsLogger(None)  # hub works without a JSONL path
    sampler = log.register(FakeSampler())
    log.register(EpochCSVLogger(csv_path))
    log.register(got.append)
    assert sampler.running  # started at registration

    log.epoch_start()
    log.log_step(0, step_time=0.5, scalars={"loss": 1.0})
    log.flush()
    elapsed = log.epoch_end()
    assert elapsed is not None and elapsed >= 0
    assert len(got) == 1 and got[0]["loss"] == 1.0
    log.close()
    assert not sampler.running  # stopped at close
    lines = open(csv_path).read().strip().splitlines()
    assert lines[0] == "timestamp,epoch_seconds"
    assert len(lines) == 2


def test_epoch_csv_errors_and_header(tmp_path):
    from pytorch_distributed_tpu.utils.csvlog import EpochCSVLogger

    csv_path = str(tmp_path / "e.csv")
    log = EpochCSVLogger(csv_path)
    with pytest.raises(RuntimeError, match="epoch_start"):
        log.epoch_end()
    for _ in range(2):
        log.epoch_start()
        log.epoch_end()
    lines = open(csv_path).read().strip().splitlines()
    assert lines[0] == "timestamp,epoch_seconds"  # header exactly once
    assert len(lines) == 3
    # pathless logger still measures but never opens a file
    nolog = EpochCSVLogger(None)
    nolog.epoch_start()
    assert nolog.epoch_end() >= 0


# ------------------------------------------------------------------- telemetry
def test_telemetry_per_sampler_peaks_do_not_cross_corrupt():
    """Client-side fallback: each sampler owns its peak dict, so concurrent
    samplers (e.g. two runs sharing a process) can't corrupt one another's
    peak column."""
    import jax.numpy as jnp

    from pytorch_distributed_tpu.utils.telemetry import sample_devices

    peaks_a, peaks_b = {}, {}
    small = jnp.ones((64, 64), jnp.float32)
    sample_devices(peaks_a)
    snap_a = dict(peaks_a)
    big = jnp.ones((512, 1024), jnp.float32)  # ~2 MiB extra live
    sample_devices(peaks_b)
    if not peaks_a and not peaks_b:
        pytest.skip("runtime exposes memory_stats; client fallback inactive")
    # B observed the bigger footprint on the device holding `big`; A's
    # peaks were not touched by B's sample.
    assert peaks_a == snap_a
    dev_big = big.addressable_shards[0].device.id
    assert peaks_b.get(dev_big, 0) >= snap_a.get(dev_big, 0) + big.nbytes
    del big, small


# ------------------------------------------------------------------ heartbeats
def test_straggler_flagging_unit():
    from pytorch_distributed_tpu.obs import find_stragglers

    now = 1000.0
    beats = {
        0: {"pid": 0, "step": 50, "t": now - 1},
        1: {"pid": 1, "step": 44, "t": now - 2},     # step lag 6
        2: {"pid": 2, "step": 50, "t": now - 300},   # stale beat
        3: {"pid": 3, "step": 49, "t": now - 3},     # healthy (lag 1)
    }
    flagged = find_stragglers(beats, now=now, max_step_lag=3, max_age_s=60)
    assert set(flagged) == {1, 2}
    assert "step lag 6" in flagged[1]
    assert "beat age" in flagged[2]
    assert find_stragglers({}, now=now) == {}


_HB_WORKER = textwrap.dedent(
    """
    import importlib.util, sys, time
    hb_dir, rank, last_step = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    # Load heartbeat.py standalone (stdlib-only by design): the monitor side
    # must work without jax, and the worker spawn stays fast.
    spec = importlib.util.spec_from_file_location("hb", %(mod)r)
    hb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(hb)
    w = hb.HeartbeatWriter(hb_dir, rank, interval_s=0.0)
    for s in range(last_step + 1):
        w.beat(s)
    """
)


def test_heartbeat_straggler_across_processes(tmp_path):
    """Two live writer processes share a heartbeat dir; the monitor flags
    the one that stopped beating at step 2 while the lead reached 10."""
    from pytorch_distributed_tpu.obs import find_stragglers, read_heartbeats

    mod = os.path.join(REPO, "pytorch_distributed_tpu", "obs", "heartbeat.py")
    script = tmp_path / "hb_worker.py"
    script.write_text(_HB_WORKER % {"mod": mod})
    hb_dir = str(tmp_path / "hb")
    procs = [
        subprocess.Popen([sys.executable, str(script), hb_dir, str(rank),
                          str(last)],
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for rank, last in ((0, 10), (1, 2))
    ]
    for p in procs:
        out, _ = p.communicate(timeout=60)
        assert p.returncode == 0, out
    beats = read_heartbeats(hb_dir)
    assert set(beats) == {0, 1}
    assert beats[0]["step"] == 10 and beats[1]["step"] == 2
    flagged = find_stragglers(beats, max_step_lag=3, max_age_s=1e9)
    assert set(flagged) == {1} and "step lag 8" in flagged[1]


def test_heartbeat_tolerates_torn_line(tmp_path):
    from pytorch_distributed_tpu.obs import HeartbeatWriter, read_heartbeats

    w = HeartbeatWriter(str(tmp_path), 0, interval_s=0.0)
    w.beat(4)
    with open(w.path, "a") as f:
        f.write('{"pid": 0, "step": 5')  # writer killed mid-append
    beats = read_heartbeats(str(tmp_path))
    assert beats[0]["step"] == 4  # newest parseable record wins


# ------------------------------------------------------------ profiler windows
def test_parse_span():
    from pytorch_distributed_tpu.obs import parse_span

    assert parse_span(None) is None
    assert parse_span("5") == (5, 6)
    assert parse_span("10:20") == (10, 20)
    with pytest.raises(ValueError):
        parse_span("20:10")
    with pytest.raises(ValueError):
        parse_span("abc")


def test_profile_window_state_machine(monkeypatch, tmp_path):
    import jax

    from pytorch_distributed_tpu.obs import ProfileWindow

    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: calls.append("start"))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append("stop"))

    # default: first trained epoch only (the seed behavior)
    pw = ProfileWindow(str(tmp_path), start_epoch=2)
    pw.epoch_begin(2)
    assert pw.epoch_end() is True
    pw.epoch_begin(3)
    assert pw.epoch_end() is False
    assert calls == ["start", "stop"]

    # epoch window + in-epoch step window → steady-state capture
    calls.clear()
    pw = ProfileWindow(str(tmp_path), epochs="1", steps="2:4")
    pw.epoch_begin(1)          # steps windowed: no start at epoch edge
    for i in range(6):
        pw.step_begin(1, i)
    assert pw.epoch_end() is False  # already stopped at step 4
    assert calls == ["start", "stop"]
    pw.epoch_begin(0)
    for i in range(6):
        pw.step_begin(0, i)    # inactive epoch: never starts
    assert calls == ["start", "stop"]

    # no profile_dir → fully inert
    pw = ProfileWindow(None)
    pw.epoch_begin(0)
    assert pw.epoch_end() is False


# ------------------------------------- acceptance: LM JSONL + in-graph norms
def test_lm_metrics_jsonl_and_eager_gradnorm(tmp_path):
    """A short LM run with metrics_jsonl produces per-step records with step
    time, throughput, loss, and a grad-norm computed in-graph that matches
    an eager recomputation on the same params/batch (ISSUE acceptance)."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.models.transformer import TransformerLM
    from pytorch_distributed_tpu.obs import REQUIRED_FIELDS, read_metrics
    from pytorch_distributed_tpu.ops import cross_entropy
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
    from pytorch_distributed_tpu.train.lm import (
        LMTrainer,
        SyntheticTokenDataset,
    )

    mesh = build_mesh(MeshSpec(("data",), (2,)), jax.devices()[:2])
    model = TransformerLM(vocab_size=32, d_model=32, n_heads=2, n_layers=1)
    ds = SyntheticTokenDataset(16, 16, 32, seed=0)
    path = str(tmp_path / "lm.jsonl")
    hb_dir = str(tmp_path / "hb")
    with mesh:
        t = LMTrainer(model, mesh, ds, batch_size=4, lr=0.05, seed=0,
                      eval_dataset=None, metrics_jsonl=path, hb_dir=hb_dir,
                      hb_interval_s=0.0)
        t.fit(3, print_freq=1)

    recs = read_metrics(path)
    assert len(recs) == 3
    for r in recs:
        for k in REQUIRED_FIELDS + ("throughput", "loss", "grad_norm",
                                    "param_norm", "lr"):
            assert k in r, k
        assert r["step_time"] > 0
        # tokens/s: 4 sequences × 16 tokens per step
        assert r["throughput"] == pytest.approx(64 / r["step_time"])

    # Eager oracle: same init (seed 0), same step-0 batch, dense loss path.
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((2, 16), jnp.int32))["params"]
    toks = jnp.asarray(ds.batch(0, 4))

    def loss_fn(p):
        logits, sown = model.apply({"params": p}, toks, mutable=["losses"])
        vocab = logits.shape[-1]
        loss = cross_entropy(logits[:, :-1].reshape(-1, vocab),
                             toks[:, 1:].reshape(-1))
        for leaf in jax.tree_util.tree_leaves(sown.get("losses", {})):
            loss = loss + leaf
        return loss

    grads = jax.grad(loss_fn)(params)
    want = float(jnp.sqrt(sum(
        jnp.sum(jnp.square(g.astype(jnp.float32)))
        for g in jax.tree_util.tree_leaves(grads))))
    assert recs[0]["grad_norm"] == pytest.approx(want, rel=1e-3)

    # heartbeats: final forced beat carries the last trained step
    from pytorch_distributed_tpu.obs import read_heartbeats

    beats = read_heartbeats(hb_dir)
    assert beats[0]["step"] == 2


# ----------------------------- acceptance: per-stage annotations in the trace
def test_pipeline_trace_contains_stage_annotations(tmp_path):
    """An XPlane trace of a 2-stage pipeline run contains the named
    per-stage annotations (pp_stage_fwd / pp_hop from parallel/pp.py, plus
    the host-side scope around the step) — ISSUE acceptance."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.obs import scope
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
    from pytorch_distributed_tpu.parallel.pp import pipeline_apply

    mesh = build_mesh(MeshSpec(("pipe",), (2,)), jax.devices()[:2])
    D = 8
    stage_params = jnp.stack([jnp.eye(D), 0.5 * jnp.eye(D)])
    x = jnp.ones((4, D), jnp.float32)

    def run(p, xx):
        return pipeline_apply(lambda w, a: jnp.tanh(a @ w), p, xx,
                              n_microbatches=2, mesh=mesh)

    step = jax.jit(run)
    step(stage_params, x).block_until_ready()  # compile outside the trace
    trace_dir = str(tmp_path / "trace")
    with jax.profiler.trace(trace_dir):
        with scope("pp_step"):
            step(stage_params, x).block_until_ready()
    pbs = glob.glob(trace_dir + "/**/*.xplane.pb", recursive=True)
    assert pbs, "no xplane.pb written"
    blob = b"".join(open(p, "rb").read() for p in pbs)
    assert b"pp_step" in blob          # host TraceAnnotation
    assert b"pp_stage_fwd" in blob     # in-graph per-stage named_scope
    assert b"pp_hop" in blob           # ring hand-off region

    # The same names are welded into the compiled HLO metadata (what a TPU
    # profile attributes self-time to).
    hlo = step.lower(stage_params, x).compile().as_text() or ""
    assert "pp_stage_fwd" in hlo and "pp_hop" in hlo


# -------------------------------------------------------------- CI satellites
def test_obs_report_selftest_runs_clean():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "obs_report.py"),
         "--selftest"],
        capture_output=True, text=True, timeout=240, env=env)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "obs_report selftest: OK" in out.stdout


def test_every_training_recipe_exposes_metrics_jsonl():
    """Lint: every public training recipe must expose --metrics-jsonl —
    either via its own parser or by sharing the Config surface (keeps
    future recipes honest)."""
    import importlib
    import inspect
    import pkgutil

    from pytorch_distributed_tpu import recipes as rpkg
    from pytorch_distributed_tpu.train import config as cfgmod

    def options(parser):
        return {s for a in parser._actions for s in a.option_strings}

    assert "--metrics-jsonl" in options(cfgmod.build_parser())
    non_training = {"lm_generate"}  # serving CLI: no train loop to meter
    checked = 0
    for m in pkgutil.iter_modules(rpkg.__path__):
        if m.name.startswith("_") or m.name in non_training:
            continue
        mod = importlib.import_module(
            f"pytorch_distributed_tpu.recipes.{m.name}")
        if hasattr(mod, "build_parser"):
            assert "--metrics-jsonl" in options(mod.build_parser()), m.name
        else:
            src = inspect.getsource(mod)
            assert "run_recipe(" in src or "parse_config(" in src, (
                f"recipe {m.name} neither builds a parser exposing "
                "--metrics-jsonl nor uses the shared Config parser")
        checked += 1
    assert checked >= 8  # the six reference recipes + tpu_native + lm_pretrain


# ----------------------------------------------------- image-harness e2e (slow)
@pytest.mark.slow
def test_trainer_e2e_obs_wiring(tmp_path):
    """Full image-Trainer epoch with every obs surface on: JSONL records
    with epoch tags, heartbeat file, headered epoch CSV, and a step-windowed
    profiler capture."""
    from pytorch_distributed_tpu.obs import read_metrics
    from pytorch_distributed_tpu.train.config import Config
    from pytorch_distributed_tpu.train.trainer import Trainer

    cfg = Config(arch="resnet18", batch_size=16, epochs=1, lr=0.1,
                 print_freq=2, synthetic=True, synthetic_length=48,
                 image_size=32, num_classes=8, seed=0,
                 checkpoint_dir=str(tmp_path), workers=2,
                 metrics_jsonl=str(tmp_path / "m.jsonl"),
                 hb_dir=str(tmp_path / "hb"), hb_interval_s=0.0,
                 epoch_csv=str(tmp_path / "e.csv"),
                 profile_dir=str(tmp_path / "prof"), profile_steps="1:2")
    Trainer(cfg).fit()
    recs = read_metrics(str(tmp_path / "m.jsonl"))
    assert len(recs) == 3  # 48 samples / batch 16
    assert all(r["epoch"] == 0 for r in recs)
    assert all("grad_norm" in r and "acc1" in r for r in recs)
    assert (tmp_path / "hb" / "heartbeat-00000.jsonl").exists()
    lines = (tmp_path / "e.csv").read_text().strip().splitlines()
    assert lines[0] == "timestamp,epoch_seconds" and len(lines) == 2
    assert glob.glob(str(tmp_path / "prof") + "/**/*.xplane.pb",
                     recursive=True)
