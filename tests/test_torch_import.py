"""Torch-checkpoint import: layout conversion + numerical architecture parity.

The strongest correctness oracle available without network egress: build a
random torchvision-shaped resnet18 state_dict, run it through an independent
torch-functional forward (eval semantics), import it with
utils/torch_import.py, and require this framework's resnet18 eval forward to
produce the same logits.  Any stride/padding/layout/BN mismatch between our
flax ResNet and the torchvision definition (the arch the reference
instantiates, reference distributed.py:134-139) shows up here as a numeric
diff — architecture parity becomes a tested property instead of a claim.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pytorch_distributed_tpu import models
from pytorch_distributed_tpu.utils.torch_import import (
    import_resnet_state_dict,
    import_torch_checkpoint,
    save_as_pretrained,
)

torch = pytest.importorskip("torch")
F = torch.nn.functional

_R18_STAGES = [2, 2, 2, 2]


def _rand_resnet18_state_dict(num_classes=13, seed=0):
    """torchvision-resnet18-shaped random weights (torch tensors)."""
    g = torch.Generator().manual_seed(seed)

    def w(*shape, scale=0.1):
        return torch.randn(*shape, generator=g) * scale

    sd = {"conv1.weight": w(64, 3, 7, 7)}

    def bn(prefix, c):
        sd[f"{prefix}.weight"] = 1.0 + 0.1 * torch.randn(c, generator=g)
        sd[f"{prefix}.bias"] = 0.1 * torch.randn(c, generator=g)
        sd[f"{prefix}.running_mean"] = 0.1 * torch.randn(c, generator=g)
        sd[f"{prefix}.running_var"] = (0.5 + torch.rand(c, generator=g))
        sd[f"{prefix}.num_batches_tracked"] = torch.tensor(7)

    bn("bn1", 64)
    widths = [64, 128, 256, 512]
    in_c = 64
    for s, (blocks, c) in enumerate(zip(_R18_STAGES, widths), start=1):
        for i in range(blocks):
            t = f"layer{s}.{i}"
            stride_block = s > 1 and i == 0
            sd[f"{t}.conv1.weight"] = w(c, in_c, 3, 3)
            bn(f"{t}.bn1", c)
            sd[f"{t}.conv2.weight"] = w(c, c, 3, 3)
            bn(f"{t}.bn2", c)
            if stride_block:
                sd[f"{t}.downsample.0.weight"] = w(c, in_c, 1, 1)
                bn(f"{t}.downsample.1", c)
            in_c = c
    sd["fc.weight"] = w(num_classes, 512)
    sd["fc.bias"] = 0.1 * torch.randn(num_classes, generator=g)
    return sd


def _torch_resnet18_eval(sd, x):
    """Independent torch-functional eval forward (torchvision semantics:
    stride on the first block of stages 2-4, BN eps 1e-5, 3x3/s2/p1
    maxpool, global avg pool, linear head)."""

    def bn(h, p):
        return F.batch_norm(
            h, sd[f"{p}.running_mean"], sd[f"{p}.running_var"],
            sd[f"{p}.weight"], sd[f"{p}.bias"], training=False, eps=1e-5,
        )

    h = F.conv2d(x, sd["conv1.weight"], stride=2, padding=3)
    h = F.relu(bn(h, "bn1"))
    h = F.max_pool2d(h, 3, stride=2, padding=1)
    for s, blocks in enumerate(_R18_STAGES, start=1):
        for i in range(blocks):
            t = f"layer{s}.{i}"
            stride = 2 if (s > 1 and i == 0) else 1
            idn = h
            out = F.conv2d(h, sd[f"{t}.conv1.weight"], stride=stride,
                           padding=1)
            out = F.relu(bn(out, f"{t}.bn1"))
            out = F.conv2d(out, sd[f"{t}.conv2.weight"], padding=1)
            out = bn(out, f"{t}.bn2")
            if f"{t}.downsample.0.weight" in sd:
                idn = bn(
                    F.conv2d(h, sd[f"{t}.downsample.0.weight"], stride=stride),
                    f"{t}.downsample.1",
                )
            h = F.relu(out + idn)
    h = h.mean(dim=(2, 3))
    return h @ sd["fc.weight"].T + sd["fc.bias"]


def test_resnet18_forward_parity_with_torch():
    sd = _rand_resnet18_state_dict()
    variables = import_resnet_state_dict(sd)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(2, 64, 64, 3)).astype(np.float32)

    with torch.no_grad():
        want = _torch_resnet18_eval(
            sd, torch.from_numpy(x.transpose(0, 3, 1, 2))
        ).numpy()

    model = models.create_model("resnet18", num_classes=13)
    got = np.asarray(model.apply(variables, jnp.asarray(x), train=False))
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=2e-3)


def test_reference_payload_unwrap_and_pretrained_roundtrip(tmp_path):
    """Reference checkpoint layout {'epoch','arch','state_dict','best_acc1'}
    with DDP 'module.' prefixes imports, saves as <arch>.msgpack, and loads
    back through the framework's own load_checkpoint."""
    sd = _rand_resnet18_state_dict(seed=1)
    payload = {
        "epoch": 3,
        "arch": "resnet18",
        "best_acc1": torch.tensor(71.25),
        "state_dict": {f"module.{k}": v for k, v in sd.items()},
    }
    variables, meta = import_torch_checkpoint(payload)
    assert meta == {"epoch": 3, "arch": "resnet18", "best_acc1": 71.25}

    path = save_as_pretrained(str(tmp_path), "resnet18", variables, meta)

    from pytorch_distributed_tpu.train.checkpoint import load_checkpoint
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState

    model = models.create_model("resnet18", num_classes=13)
    init = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)),
                      train=False)
    template = TrainState.create(init, sgd_init(init["params"]))
    state, meta2 = load_checkpoint(path, template)
    assert meta2["arch"] == "resnet18" and meta2["best_acc1"] == 71.25
    got = np.asarray(
        state.params["conv_init"]["kernel"]
    )
    want = sd["conv1.weight"].numpy().transpose(2, 3, 1, 0)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)


def test_bottleneck_structure_import_matches_model_tree():
    """resnet50-shaped keys (conv3 ⇒ Bottleneck) produce exactly the
    flax tree create_model('resnet50') builds."""
    import flax

    g = torch.Generator().manual_seed(2)

    def w(*shape):
        return torch.randn(*shape, generator=g) * 0.05

    sd = {"conv1.weight": w(64, 3, 7, 7)}

    def bn(prefix, c):
        for k, v in (("weight", torch.ones(c)), ("bias", torch.zeros(c)),
                     ("running_mean", torch.zeros(c)),
                     ("running_var", torch.ones(c))):
            sd[f"{prefix}.{k}"] = v

    bn("bn1", 64)
    stages, widths = [3, 4, 6, 3], [64, 128, 256, 512]
    in_c = 64
    for s, (blocks, c) in enumerate(zip(stages, widths), start=1):
        for i in range(blocks):
            t = f"layer{s}.{i}"
            stride_block = i == 0
            sd[f"{t}.conv1.weight"] = w(c, in_c, 1, 1)
            bn(f"{t}.bn1", c)
            sd[f"{t}.conv2.weight"] = w(c, c, 3, 3)
            bn(f"{t}.bn2", c)
            sd[f"{t}.conv3.weight"] = w(4 * c, c, 1, 1)
            bn(f"{t}.bn3", 4 * c)
            if stride_block:
                sd[f"{t}.downsample.0.weight"] = w(4 * c, in_c, 1, 1)
                bn(f"{t}.downsample.1", 4 * c)
            in_c = 4 * c
    sd["fc.weight"] = w(5, 2048)
    sd["fc.bias"] = torch.zeros(5)

    variables = import_resnet_state_dict(sd)
    model = models.create_model("resnet50", num_classes=5)
    ref = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 64, 64, 3)), train=False)
    )
    for coll in ("params", "batch_stats"):
        want = flax.traverse_util.flatten_dict(ref[coll])
        got = flax.traverse_util.flatten_dict(variables[coll])
        assert set(want) == set(got), coll
        for k in want:
            assert tuple(want[k].shape) == tuple(got[k].shape), k
