"""Ring attention vs dense oracle on a ('data', 'seq') mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
from pytorch_distributed_tpu.parallel.ring import (
    dense_attention,
    ring_self_attention,
)


def _qkv(B=2, L=32, H=4, D=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(B, L, H, D)).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("mesh_shape", [("seq", 8), ("data_seq", None)])
def test_ring_matches_dense(causal, mesh_shape):
    if mesh_shape[0] == "seq":
        mesh = build_mesh(MeshSpec(("seq",), (8,)), jax.devices()[:8])
    else:
        mesh = build_mesh(MeshSpec(("data", "seq"), (2, 4)), jax.devices()[:8])
    q, k, v = _qkv()
    want = dense_attention(q, k, v, causal=causal)
    got = ring_self_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_gradients_match_dense():
    mesh = build_mesh(MeshSpec(("seq",), (8,)), jax.devices()[:8])
    q, k, v = _qkv(L=16)

    def loss_ring(q, k, v):
        return jnp.sum(ring_self_attention(q, k, v, mesh, causal=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5)


def test_ring_bf16_inputs():
    mesh = build_mesh(MeshSpec(("seq",), (8,)), jax.devices()[:8])
    q, k, v = _qkv()
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    got = ring_self_attention(qb, kb, vb, mesh, causal=True)
    assert got.dtype == jnp.bfloat16
    want = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(got, dtype=np.float32), np.asarray(want), rtol=5e-2, atol=5e-2
    )


def test_causal_first_token_attends_only_itself():
    """Row 0 of causal attention must equal v[0] exactly — any leakage from
    future positions (a block-masking bug) breaks this invariant."""
    mesh = build_mesh(MeshSpec(("seq",), (8,)), jax.devices()[:8])
    q, k, v = _qkv(B=1, L=16, H=2, D=8)
    out = ring_self_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(
        np.asarray(out[0, 0]), np.asarray(v[0, 0]), rtol=1e-5, atol=1e-6
    )
