"""Efficiency accounting (ISSUE 5): analytic FLOPs vs XLA cost_analysis
parity, MFU/HFU plumbing through both trainers, the goodput/badput
ledger, the recompile watchdog, heartbeat slow-vs-dead discrimination,
and the obs_report --diff regression fence."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ----------------------------------------------------- analytic FLOPs parity
def test_resnet_flops_parity_vs_cost_analysis():
    """Analytic image step cost within +-10% of the compiler's own count
    for a tiny resnet on the 4-way CPU mesh (ISSUE acceptance)."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu import models
    from pytorch_distributed_tpu.obs.flops import (
        image_step_cost,
        xla_step_flops,
    )
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState
    from pytorch_distributed_tpu.train.steps import make_train_step

    mesh = build_mesh(MeshSpec(("data",), (4,)), jax.devices()[:4])
    B, IM, NC = 8, 32, 8
    model = models.create_model("resnet18", num_classes=NC)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, IM, IM, 3)), train=False)
    state = TrainState.create(variables, sgd_init(variables["params"]))
    step = make_train_step(model, mesh)
    batch = {"images": jnp.zeros((B, IM, IM, 3)),
             "labels": jnp.zeros((B,), jnp.int32),
             "weights": jnp.ones((B,), jnp.float32)}

    cost = image_step_cost("resnet18", B, IM, NC)
    # the analytic param count backs the optimizer term — sanity it first
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(variables["params"]))
    assert cost.params == pytest.approx(n_params, rel=0.01)

    est = cost.per_device_flops(4)
    xla = xla_step_flops(step, state, batch, jnp.float32(0.1))
    assert 0.9 <= xla / est <= 1.1, (xla, est, xla / est)


def test_lm_flops_parity_vs_cost_analysis():
    """Analytic LM step cost within +-10% of cost_analysis for a tiny LM
    on the 4-way CPU mesh (ISSUE acceptance)."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.models.transformer import TransformerLM
    from pytorch_distributed_tpu.obs.flops import (
        lm_step_cost_for,
        xla_step_flops,
    )
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
    from pytorch_distributed_tpu.parallel.tp import (
        replicated_like,
        shard_state,
    )
    from pytorch_distributed_tpu.train.lm import make_lm_train_step
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState

    mesh = build_mesh(MeshSpec(("data",), (4,)), jax.devices()[:4])
    V, D, H, L, B, S = 64, 64, 4, 2, 8, 32
    model = TransformerLM(vocab_size=V, d_model=D, n_heads=H, n_layers=L,
                          attn_impl="dense")
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((4, S), jnp.int32))["params"]
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(params))
    specs = replicated_like(params)
    state = shard_state(
        TrainState.create({"params": params}, sgd_init(params)), specs, mesh)
    step = make_lm_train_step(model, mesh, specs)

    cost = lm_step_cost_for(model, B, S)
    assert cost.params == pytest.approx(n_params, rel=0.01)
    est = cost.per_device_flops(4)
    xla = xla_step_flops(step, state, jnp.zeros((B, S), jnp.int32),
                         jnp.float32(0.1))
    assert 0.9 <= xla / est <= 1.1, (xla, est, xla / est)


def test_step_cost_taxes_and_reporter():
    """Remat and fused-CE recompute inflate hardware FLOPs only (HFU < MFU
    denominator relationship), and the reporter turns seconds into
    percentages with the expected arithmetic."""
    from pytorch_distributed_tpu.obs.flops import (
        MFUReporter,
        image_step_cost,
        lm_step_cost,
    )

    plain = lm_step_cost(256, 64, 2, 8, 32)
    fused = lm_step_cost(256, 64, 2, 8, 32, fused_ce=True)
    remat = lm_step_cost(256, 64, 2, 8, 32, remat=True)
    assert plain.hardware_flops == plain.model_flops
    assert fused.hardware_flops > fused.model_flops
    assert remat.hardware_flops > remat.model_flops
    # fused-CE trims the head to the loss rows: model FLOPs drop slightly
    assert fused.model_flops < plain.model_flops

    vit = image_step_cost("vit_b_16", 4, 224, 1000)
    vit_r = image_step_cost("vit_b_16", 4, 224, 1000, remat=True)
    # the ~1/3-extra-matmul remat tax (models/vit.py)
    tax = (vit_r.hardware_flops - vit.model_flops) / vit.model_flops
    assert 0.2 < tax < 0.4

    with pytest.raises(ValueError, match="analytic FLOPs model"):
        image_step_cost("densenet121", 8, 32, 8)

    rep = MFUReporter(plain, n_devices=4, peak_per_chip=1e12)
    fields = rep.fields(0.5)
    assert fields["mfu"] == pytest.approx(
        100.0 * plain.model_flops / 0.5 / 4e12)
    assert fields["hfu"] >= fields["mfu"]
    assert fields["model_tflops"] > 0


def test_device_peak_flops_table_and_override(monkeypatch):
    from pytorch_distributed_tpu.obs.flops import (
        CPU_FALLBACK_PEAK,
        device_peak_flops,
    )

    class FakeDev:
        def __init__(self, kind):
            self.device_kind = kind

    assert device_peak_flops(FakeDev("TPU v5e")) == 197e12
    assert device_peak_flops(FakeDev("TPU v4")) == 275e12
    assert device_peak_flops(FakeDev("weird accelerator")) == CPU_FALLBACK_PEAK
    monkeypatch.setenv("PTD_TPU_PEAK_FLOPS", "123e9")
    assert device_peak_flops(FakeDev("TPU v4")) == 123e9


# -------------------------------------------------------- recompile watchdog
def test_watchdog_flags_planted_recompile():
    """A dynamic-shape recompile after warmup raises exactly one anomaly
    event (ISSUE acceptance)."""
    import jax.numpy as jnp

    from pytorch_distributed_tpu.obs import MetricsLogger, RecompileWatchdog

    import jax

    events = []
    obs = MetricsLogger(None)
    obs.register(events.append)
    f = jax.jit(lambda x: x * 2 + 1)
    # inputs built OUTSIDE the watched region (array creation is itself a
    # tiny compile — the trainers' feeders run outside the watch too)
    x8, x9 = jnp.ones(8), jnp.ones(9)
    with RecompileWatchdog(obs=obs) as wd:
        with wd.watch("step_fn", step=0):
            f(x8).block_until_ready()              # warmup compile
        with wd.watch("step_fn", step=1):
            f(x8).block_until_ready()              # cached: no compile
        assert wd.compiles.get("step_fn") == 1 and not wd.anomalies
        with wd.watch("step_fn", step=2):
            f(x9).block_until_ready()              # planted dynamic shape
    assert wd.compiles["step_fn"] == 2
    assert len(wd.anomalies) == 1, wd.anomalies
    a = wd.anomalies[0]
    assert a["label"] == "step_fn" and a["step"] == 2
    assert a["duration_s"] > 0
    # the anomaly reached the metrics stream as a recompile ft_event
    recs = [e for e in events if e.get("ft_event") == "recompile"]
    assert len(recs) == 1 and recs[0]["label"] == "step_fn"
    # unattributed compiles are counted but never flagged
    g = jax.jit(lambda x: x - 1)
    g(jnp.ones(3)).block_until_ready()
    assert len(wd.anomalies) == 1


def test_watchdog_uninstall_stops_counting():
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.obs import RecompileWatchdog

    wd = RecompileWatchdog().install()
    wd.uninstall()
    f = jax.jit(lambda x: x + 3)
    with wd.watch("dead"):
        f(jnp.ones(4)).block_until_ready()
    assert "dead" not in wd.compiles


# ------------------------------------------------------------ goodput ledger
def _step_rec(step, t, st=1.0):
    return {"step": step, "t": t, "process": 0, "step_time": st,
            "step_time_ema": st, "step_time_p50": st, "step_time_p95": st,
            "step_time_max": st}


def test_goodput_ledger_taxonomy():
    """Synthetic JSONL with skip/rollback/preempt events lands in the
    right badput buckets (ISSUE acceptance)."""
    from pytorch_distributed_tpu.obs.goodput import compute_goodput

    t0 = 1000.0
    records = [_step_rec(i, t0 + i + 1) for i in range(10)]
    records += [
        {"ft_event": "skip", "step": 7, "t": t0 + 8.1},
        {"ft_event": "rollback", "step": 9, "restored_step": 5,
         "t": t0 + 10.1, "lr_scale": 0.5},
        {"ft_event": "preempt", "step": 9, "t": t0 + 10.2},
    ]
    # resumed run: 30s restart gap, then 3 more steps
    records += [_step_rec(10 + i, t0 + 40.2 + i) for i in range(3)]
    rep = compute_goodput(records)
    assert rep.steps == 13
    assert rep.counts["nan_skip"] == 1
    assert rep.badput_s["nan_skip"] == pytest.approx(1.0)
    # rollback discards steps 6..9, minus step 7 already booked as skip
    assert rep.counts["rollback_discard"] == 1
    assert rep.badput_s["rollback_discard"] == pytest.approx(3.0)
    assert rep.counts["preempt_gap"] == 1
    assert rep.badput_s["preempt_gap"] == pytest.approx(30.0, abs=0.2)
    # productive = 13 steps - 1 skip - 3 discarded
    assert rep.productive_s == pytest.approx(9.0)
    assert 0 < rep.goodput_pct < 100


def test_goodput_stall_detection_and_summary():
    from pytorch_distributed_tpu.obs.goodput import (
        compute_goodput,
        summarize_goodput,
    )

    t0 = 0.0
    records = [_step_rec(i, t0 + i + 1, st=1.0) for i in range(5)]
    # 20s unexplained gap before step 5 (data starvation)
    records += [_step_rec(5 + i, t0 + 25.0 + i, st=1.0) for i in range(3)]
    rep = compute_goodput(records)
    assert rep.counts["stall"] == 1
    assert rep.badput_s["stall"] == pytest.approx(19.0, abs=0.2)
    lines = summarize_goodput(records)
    text = "\n".join(lines)
    assert "== goodput ==" in text and "badput/stall" in text
    assert "goodput" in text
    # tiny jitter below the floor is NOT a stall
    clean = [_step_rec(i, i * 1.1, st=1.0) for i in range(10)]
    assert compute_goodput(clean).counts["stall"] == 0


def test_goodput_tracker_live_sink():
    from pytorch_distributed_tpu.obs import MetricsLogger
    from pytorch_distributed_tpu.obs.goodput import GoodputTracker

    log = MetricsLogger(None)
    tracker = log.register(GoodputTracker())
    for i in range(5):
        log.log_step(i, step_time=0.5, n_items=8)
    log.log_event("skip", step=3, consecutive=1)
    log.flush()
    rep = tracker.report()
    assert rep.steps == 5 and rep.counts["nan_skip"] == 1
    assert "goodput" in tracker.format_summary()
    log.close()


# ------------------------------------------- heartbeat slow-vs-dead satellite
def test_find_stragglers_slow_vs_dead():
    from pytorch_distributed_tpu.obs import find_stragglers

    now = 1000.0
    beats = {
        0: {"pid": 0, "step": 50, "t": now - 1, "ema": 0.010},
        # lagging with FRESH beats and a fat EMA: a slow rank
        1: {"pid": 1, "step": 40, "t": now - 2, "ema": 0.055},
        # stale beats: dead or hung, with its last ft_event on record
        2: {"pid": 2, "step": 50, "t": now - 300, "ema": 0.010,
            "last_ft": "preempt"},
        3: {"pid": 3, "step": 49, "t": now - 1, "ema": 0.011},
    }
    flagged = find_stragglers(beats, now=now, max_step_lag=3, max_age_s=60)
    assert set(flagged) == {1, 2}
    assert "slow rank" in flagged[1] and "ema" in flagged[1]
    assert "dead or hung" in flagged[2]
    assert "last ft_event: preempt" in flagged[2]
    # without EMAs the legacy reasons still work
    legacy = {0: {"pid": 0, "step": 50, "t": now - 1},
              1: {"pid": 1, "step": 40, "t": now - 2}}
    flagged = find_stragglers(legacy, now=now, max_step_lag=3, max_age_s=60)
    assert "step lag 10" in flagged[1] and "slow rank" not in flagged[1]


def test_heartbeat_beats_carry_ema_and_ft(tmp_path):
    from pytorch_distributed_tpu.obs import HeartbeatWriter, read_heartbeats

    w = HeartbeatWriter(str(tmp_path), 0, interval_s=0.0)
    w.beat(3, step_time_ema=0.02, last_ft="rollback")
    beats = read_heartbeats(str(tmp_path))
    assert beats[0]["ema"] == pytest.approx(0.02)
    assert beats[0]["last_ft"] == "rollback"


# ---------------------------------------------------- bench staleness events
def test_benchlib_bench_event_and_report_fold(tmp_path, monkeypatch):
    """A stale-probe bench_event lands in the metrics-stream schema and
    obs_report folds it into a '== bench ==' section."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import benchlib
    import obs_report

    path = str(tmp_path / "bench_events.jsonl")
    monkeypatch.setenv("BENCH_EVENTS_JSONL", path)
    benchlib.bench_event("stale", reason="device discovery hung >40s",
                         last_good="2026-07-31T06:32:08+0000",
                         metric="resnet50_train_images_per_sec_per_chip",
                         value=2511.3)
    recs, malformed = obs_report.load_metrics(path)
    assert malformed == 0 and recs[0]["bench_event"] == "stale"
    assert recs[0]["t"] > 0  # same time-stamped JSONL schema as obs records
    lines = obs_report.summarize_bench(recs)
    text = "\n".join(lines)
    assert "== bench ==" in text and "stale" in text
    assert "last good 2026-07-31" in text and "hung" in text
    # unwritable path: best-effort, never raises (bench emission survives)
    monkeypatch.setenv("BENCH_EVENTS_JSONL",
                       str(tmp_path / "no" / "such" / "dir" / "x.jsonl"))
    benchlib.bench_event("stale", reason="r")


# ------------------------------------------------------- obs_report diff fence
def test_obs_report_diff_verdicts(tmp_path):
    """REGRESS on a synthetically slowed run, PASS on identical runs, and
    malformed-line counting (ISSUE acceptance)."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import obs_report

    def write_run(path, st):
        with open(path, "w") as f:
            for i in range(20):
                f.write(json.dumps(_step_rec(i, 100.0 + i * st, st=st)
                                   | {"throughput": 64 / st,
                                      "mfu": 30.0 * 0.01 / st}) + "\n")

    a = str(tmp_path / "a.jsonl")
    b = str(tmp_path / "b.jsonl")
    write_run(a, 0.010)
    write_run(b, 0.013)
    with open(b, "a") as f:
        f.write('{"step": 20, "step_ti')  # torn tail
    rc = obs_report.main(["--diff", a, b])
    assert rc == 1  # regression fence trips
    rc = obs_report.main(["--diff", a, a])
    assert rc == 0
    recs, malformed = obs_report.load_metrics(b)
    assert len(recs) == 20 and malformed == 1
    text, regressed = obs_report.diff_report(recs, recs)
    assert not regressed and "overall: PASS" in text


# ----------------------------------------------- trainer wiring (LM fast path)
def test_lm_trainer_mfu_goodput_watchdog_clean_run(tmp_path):
    """A clean LMTrainer run with --mfu/--goodput/--watch-recompiles on:
    MFU/HFU fields in every record, a silent watchdog (no post-warmup
    recompiles), and a live goodput summary."""
    import jax

    from pytorch_distributed_tpu.models.transformer import TransformerLM
    from pytorch_distributed_tpu.obs import read_metrics
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
    from pytorch_distributed_tpu.train.lm import (
        LMTrainer,
        SyntheticTokenDataset,
    )

    mesh = build_mesh(MeshSpec(("data",), (2,)), jax.devices()[:2])
    model = TransformerLM(vocab_size=32, d_model=32, n_heads=2, n_layers=1)
    ds = SyntheticTokenDataset(16, 16, 32, seed=0)
    path = str(tmp_path / "lm.jsonl")
    with mesh:
        t = LMTrainer(model, mesh, ds, batch_size=4, lr=0.05, seed=0,
                      eval_dataset=None, metrics_jsonl=path,
                      mfu=True, goodput=True, watch_recompiles=True)
        t.fit(4, print_freq=2)
    recs = [r for r in read_metrics(path) if "ft_event" not in r]
    assert len(recs) == 4
    for r in recs:
        assert r["mfu"] > 0 and r["hfu"] >= r["mfu"]
        assert r["model_tflops"] > 0
    assert t.watchdog.compiles.get("lm_step") == 1
    assert t.watchdog.anomalies == []
    assert t._goodput.report().steps == 4
    # no recompile events polluted the stream
    assert not any(r.get("ft_event") == "recompile"
                   for r in read_metrics(path))


# ------------------------------------------ image trainer clean 2-epoch (slow)
@pytest.mark.slow
def test_image_trainer_watchdog_silent_two_epochs(tmp_path):
    """The watchdog stays silent across a clean 2-epoch image run with all
    efficiency surfaces on (ISSUE acceptance: no false positives), and the
    JSONL carries MFU fields for the resnet family."""
    from pytorch_distributed_tpu.obs import read_metrics
    from pytorch_distributed_tpu.train.config import Config
    from pytorch_distributed_tpu.train.trainer import Trainer

    cfg = Config(arch="resnet18", batch_size=16, epochs=2, lr=0.1,
                 print_freq=2, synthetic=True, synthetic_length=32,
                 image_size=32, num_classes=8, seed=0,
                 checkpoint_dir=str(tmp_path), workers=2,
                 metrics_jsonl=str(tmp_path / "m.jsonl"),
                 hb_dir=str(tmp_path / "hb"), hb_interval_s=0.0,
                 mfu=True, goodput=True, watch_recompiles=True)
    tr = Trainer(cfg)
    tr.fit()
    assert tr.watchdog.anomalies == [], tr.watchdog.anomalies
    assert tr.watchdog.compiles.get("train_step") == 1
    assert tr.watchdog.compiles.get("eval_step", 0) >= 1
    recs = [r for r in read_metrics(str(tmp_path / "m.jsonl"))
            if "ft_event" not in r]
    assert len(recs) == 4  # 32 samples / batch 16, 2 epochs
    assert all(r["mfu"] > 0 and r["hfu"] >= r["mfu"] for r in recs)
    # beats carry the EMA for the slow-vs-dead monitor
    from pytorch_distributed_tpu.obs import read_heartbeats

    beats = read_heartbeats(str(tmp_path / "hb"))
    assert beats[0].get("ema", 0) > 0
