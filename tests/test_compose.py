"""Composed parallelism: dp × sp × tp in ONE mesh for the TransformerLM.

The round-1 gap was one-axis-at-a-time; these tests pin the composition:
a 2×2×2 (data × seq × model) mesh must produce the same step numerics as
pure replicated DP at small shapes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.models.transformer import TransformerLM
from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
from pytorch_distributed_tpu.parallel.tp import replicated_like, tp_specs
from pytorch_distributed_tpu.train.lm import LMTrainer, SyntheticTokenDataset, make_lm_train_step
from pytorch_distributed_tpu.train.optim import sgd_init
from pytorch_distributed_tpu.train.state import TrainState

VOCAB, D, HEADS, LAYERS, SEQ, BATCH = 64, 32, 2, 2, 32, 8


@pytest.fixture(scope="module")
def tokens():
    rng = np.random.default_rng(0)
    return rng.integers(0, VOCAB, size=(BATCH, SEQ)).astype(np.int32)


@pytest.fixture(scope="module")
def replicated_baseline(tokens):
    """The pure-DP 8-device one-step reference (params + metrics): one
    compile for every composed-mesh parity test in the module (the
    compile-budget discipline: tests/conftest.py ``lm_world32``)."""
    mesh = build_mesh(MeshSpec(("data",), (8,)), jax.devices()[:8])
    model = TransformerLM(vocab_size=VOCAB, d_model=D, n_heads=HEADS,
                          n_layers=LAYERS)
    return _run_one_step(mesh, model, None, tokens)


def _run_one_step(mesh, model, specs, tokens):
    from jax.sharding import NamedSharding, PartitionSpec as P
    from pytorch_distributed_tpu.parallel.tp import shard_state

    with mesh:
        tokens0 = jnp.zeros((dict(mesh.shape).get("data", 1), SEQ), jnp.int32)
        variables = model.init(jax.random.PRNGKey(0), tokens0)
        params = variables["params"]
        sp = specs if specs is not None else replicated_like(params)
        state = TrainState.create({"params": params}, sgd_init(params))
        state = shard_state(state, sp, mesh)
        step = make_lm_train_step(model, mesh, sp, weight_decay=0.0)
        toks = jax.device_put(
            tokens, NamedSharding(mesh, P("data", None)))
        new_state, metrics = step(state, toks, jnp.float32(0.05))
        return (
            jax.device_get(new_state.params),
            {k: float(v) for k, v in metrics.items()},
        )


def test_dp_sp_tp_composed_matches_replicated(tokens, replicated_baseline):
    base_params, base_metrics = replicated_baseline

    mesh = build_mesh(MeshSpec(("data", "seq", "model"), (2, 2, 2)),
                      jax.devices()[:8])
    model = TransformerLM(vocab_size=VOCAB, d_model=D, n_heads=HEADS,
                          n_layers=LAYERS, mesh=mesh, ring=True)
    params_shape = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((2, SEQ), jnp.int32))
    )["params"]
    specs = tp_specs(params_shape)
    comp_params, comp_metrics = _run_one_step(mesh, model, specs, tokens)

    assert base_metrics["loss"] == pytest.approx(comp_metrics["loss"],
                                                 rel=2e-4)
    assert base_metrics["acc"] == pytest.approx(comp_metrics["acc"], abs=1e-3)
    flat_a = jax.tree_util.tree_leaves_with_path(base_params)
    flat_b = dict(
        (jax.tree_util.keystr(p), l)
        for p, l in jax.tree_util.tree_leaves_with_path(comp_params)
    )
    for path, leaf in flat_a:
        key = jax.tree_util.keystr(path)
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(flat_b[key]),
            rtol=5e-4, atol=5e-5, err_msg=key)


def test_lm_pretrain_tp_plus_sp_runs_and_learns(capsys, tmp_path):
    from pytorch_distributed_tpu.recipes import lm_pretrain

    final = lm_pretrain.main([
        "--vocab", "32", "--d-model", "32", "--n-heads", "2",
        "--n-layers", "1", "--seq-len", "32", "-b", "8",
        "--steps", "15", "--lr", "0.05", "-p", "4",
        "--dataset-length", "8", "--precision", "fp32",
        "--tp", "2", "--sp", "2", "--no-eval",
        "--checkpoint-dir", str(tmp_path),
    ])
    out = capsys.readouterr().out
    assert "Final loss" in out
    first = float(out.split("Loss ")[1].split(" ")[0])
    assert final < first  # learnable affine process, composed mesh
    assert (tmp_path / "checkpoint.msgpack").exists()


def test_lm_pretrain_pp_runs_and_learns(capsys, tmp_path):
    from pytorch_distributed_tpu.recipes import lm_pretrain

    final = lm_pretrain.main([
        "--vocab", "32", "--d-model", "32", "--n-heads", "2",
        "--n-layers", "4", "--seq-len", "32", "-b", "8",
        "--steps", "15", "--lr", "0.05", "-p", "4",
        "--dataset-length", "8", "--precision", "fp32",
        "--pp", "4", "--no-eval",
        "--checkpoint-dir", str(tmp_path),
    ])
    out = capsys.readouterr().out
    assert "Final loss" in out
    first = float(out.split("Loss ")[1].split(" ")[0])
    assert final < first
    assert (tmp_path / "checkpoint.msgpack").exists()
