"""Live multi-process bootstrap: two real processes rendezvous through the
PTD_TPU_* env contract (the reference's tcp://127.0.0.1:23456 analogue,
multiprocessing_distributed.py:132-135), form one 2-device global mesh, and
agree on a cross-process collective."""

import os
import subprocess
import sys
import textwrap

import pytest

_WORKER = textwrap.dedent(
    """
    import os, sys
    pid = sys.argv[1]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    os.environ["PTD_TPU_COORDINATOR"] = "127.0.0.1:%(port)d"
    os.environ["PTD_TPU_NUM_PROCESSES"] = "2"
    os.environ["PTD_TPU_PROCESS_ID"] = pid
    sys.path.insert(0, %(repo)r)
    import jax
    jax.config.update("jax_platforms", "cpu")
    from pytorch_distributed_tpu.parallel import initialize, data_parallel_mesh
    ctx = initialize()
    assert ctx.process_count == 2
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = data_parallel_mesh()
    local = np.full((2, 4), float(ctx.process_index), np.float32)
    arr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("data")), local)
    total = jax.jit(lambda x: jnp.sum(x),
                    out_shardings=NamedSharding(mesh, P()))(arr)
    print(f"RESULT {ctx.process_index} {float(total)}", flush=True)
    """
)


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(tmp_path, template, n_procs, extra_args=(), timeout=540):
    """Shared spawn harness: write the worker template (filling port/repo),
    launch ``n_procs`` ranks (rank as argv[1]), kill stragglers, assert
    every rank exited 0, and return the per-rank stdout list."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "worker.py"
    script.write_text(template % {"port": _free_port(), "repo": repo})
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PTD_TPU", "JAX_", "XLA_"))}
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i)] + [str(a) for a in extra_args],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        for i in range(n_procs)
    ]
    try:
        outs = [p.communicate(timeout=timeout)[0] for p in procs]
    finally:
        for p in procs:  # no orphaned workers holding the port on timeout
            if p.poll() is None:
                p.kill()
    for i, out in enumerate(outs):
        assert procs[i].returncode == 0, out
    return outs


def _parse(outs, prefix):
    """Collect ``{rank: payload}`` from lines ``<prefix> <rank> <payload>``."""
    vals = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith(prefix + " "):
                parts = line.split(" ", 2)
                vals[int(parts[1])] = parts[2] if len(parts) > 2 else ""
    return vals


def test_two_process_rendezvous_and_collective(tmp_path):
    outs = _run_workers(tmp_path, _WORKER, 2, timeout=300)
    # 2x4 zeros from proc 0 + 2x4 ones from proc 1 ⇒ global sum 8.
    results = _parse(outs, "RESULT")
    assert results == {0: "8.0", 1: "8.0"}, outs


_TRAINER_WORKER = textwrap.dedent(
    """
    import os, sys, json
    pid = sys.argv[1]
    ckpt_dir = sys.argv[2]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    os.environ["PTD_TPU_COORDINATOR"] = "127.0.0.1:%(port)d"
    os.environ["PTD_TPU_NUM_PROCESSES"] = "2"
    os.environ["PTD_TPU_PROCESS_ID"] = pid
    sys.path.insert(0, %(repo)r)
    import jax
    jax.config.update("jax_platforms", "cpu")
    from pytorch_distributed_tpu.parallel import initialize
    ctx = initialize()
    from pytorch_distributed_tpu.train.config import Config
    from pytorch_distributed_tpu.train.trainer import Trainer
    cfg = Config(arch="resnet18", batch_size=8, epochs=1, print_freq=1,
                 seed=0, synthetic=True, synthetic_length=32, image_size=32,
                 num_classes=4, checkpoint_dir=ckpt_dir, workers=2)
    t = Trainer(cfg, ctx=ctx)
    t.train_sampler.set_epoch(0)
    idx, valid = t.train_sampler.shard()
    shard = sorted(int(i) for i, v in zip(idx, valid) if v)
    print("SHARD", ctx.process_index, json.dumps(shard), flush=True)
    best = t.fit()
    print("ACC", ctx.process_index, f"{best:.6f}", flush=True)
    """
)


def test_two_process_trainer_epoch(tmp_path):
    """Full 1-epoch Trainer in 2 live processes (reference behavior being
    verified: per-rank DistributedSampler shards + all-reduced metrics +
    rank-0-only checkpoint, distributed.py:174-175,218-225)."""
    import json

    ckpt_dir = tmp_path / "ckpt"
    outs = _run_workers(tmp_path, _TRAINER_WORKER, 2, extra_args=[ckpt_dir])
    shards = {r: json.loads(p) for r, p in _parse(outs, "SHARD").items()}
    accs = {r: float(p) for r, p in _parse(outs, "ACC").items()}

    # Disjoint shards covering the dataset exactly once (len 32, world 2).
    assert set(shards) == {0, 1}
    s0, s1 = set(shards[0]), set(shards[1])
    assert len(shards[0]) == len(shards[1]) == 16
    assert not (s0 & s1)
    assert s0 | s1 == set(range(32))

    # Identical global metrics on both ranks (in-graph all-reduce).
    assert set(accs) == {0, 1}
    assert accs[0] == accs[1]

    # Exactly one rank wrote the checkpoint.
    files = sorted(p.name for p in ckpt_dir.iterdir())
    assert files.count("checkpoint.msgpack") == 1, files


_LM_WORKER = textwrap.dedent(
    """
    import os, sys, json
    pid = sys.argv[1]
    ckpt_dir = sys.argv[2]
    tp = int(sys.argv[3])
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    os.environ["PTD_TPU_COORDINATOR"] = "127.0.0.1:%(port)d"
    os.environ["PTD_TPU_NUM_PROCESSES"] = "2"
    os.environ["PTD_TPU_PROCESS_ID"] = pid
    sys.path.insert(0, %(repo)r)
    import jax
    jax.config.update("jax_platforms", "cpu")
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh, initialize
    ctx = initialize()
    assert ctx.process_count == 2
    from pytorch_distributed_tpu.models.transformer import TransformerLM
    from pytorch_distributed_tpu.parallel.tp import tp_specs
    from pytorch_distributed_tpu.train.lm import LMTrainer, SyntheticTokenDataset
    import jax.numpy as jnp
    if tp > 1:
        mesh = build_mesh(MeshSpec(("data", "model"), (1, 2)))
        specs_from = "tp"
    else:
        mesh = build_mesh(MeshSpec(("data",), (2,)))
        specs_from = None
    model = TransformerLM(vocab_size=32, d_model=32, n_heads=2, n_layers=1)
    ds = SyntheticTokenDataset(16, 16, 32)
    eval_ds = SyntheticTokenDataset(8, 16, 32, seed=1)
    with mesh:
        specs = None
        if specs_from == "tp":
            shapes = jax.eval_shape(
                lambda: model.init(jax.random.PRNGKey(0),
                                   jnp.zeros((1, 16), jnp.int32)))["params"]
            specs = tp_specs(shapes)
        t = LMTrainer(model, mesh, ds, batch_size=8, lr=0.05,
                      param_specs=specs, is_primary=ctx.is_primary,
                      checkpoint_dir=ckpt_dir, eval_dataset=eval_ds,
                      eval_batches=2, hb_dir=ckpt_dir + "_hb",
                      hb_interval_s=0.0)
        rows = t._local_rows(ds.batch(0, 8))
        print("ROWS", ctx.process_index, rows.shape[0],
              json.dumps(rows[:, 0].tolist()), flush=True)
        final = t.fit(8, print_freq=4)
        loss, ppl, acc = t.evaluate()
    print("METRICS", ctx.process_index,
          f"{final:.6f} {loss:.6f} {ppl:.4f}", flush=True)
    """
)


_GRID_WORKER = textwrap.dedent(
    """
    import os, sys, json
    pid = sys.argv[1]
    ckpt_dir = sys.argv[2]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    os.environ["PTD_TPU_COORDINATOR"] = "127.0.0.1:%(port)d"
    os.environ["PTD_TPU_NUM_PROCESSES"] = "4"
    os.environ["PTD_TPU_PROCESS_ID"] = pid
    sys.path.insert(0, %(repo)r)
    import jax
    jax.config.update("jax_platforms", "cpu")
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh, initialize
    ctx = initialize()
    assert ctx.process_count == 4
    from pytorch_distributed_tpu.models.transformer import TransformerLM
    from pytorch_distributed_tpu.parallel.tp import tp_specs
    from pytorch_distributed_tpu.train.lm import LMTrainer, SyntheticTokenDataset
    import jax.numpy as jnp
    # The deployment-shaped grid: model axis innermost (fast ICI hops),
    # data across the outer pairs — dp=2 x tp=2 over 4 single-device procs.
    mesh = build_mesh(MeshSpec(("data", "model"), (2, 2)))
    model = TransformerLM(vocab_size=32, d_model=32, n_heads=2, n_layers=1)
    ds = SyntheticTokenDataset(16, 16, 32)
    with mesh:
        shapes = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 16), jnp.int32)))["params"]
        t = LMTrainer(model, mesh, ds, batch_size=8, lr=0.05,
                      param_specs=tp_specs(shapes), is_primary=ctx.is_primary,
                      checkpoint_dir=ckpt_dir)
        rows = t._local_rows(ds.batch(0, 8))
        print("ROWS", ctx.process_index, rows.shape[0],
              json.dumps(rows[:, 0].tolist()), flush=True)
        final = t.fit(6, print_freq=3)
    print("METRICS", ctx.process_index, f"{final:.6f}", flush=True)
    """
)


def test_four_process_dp_tp_grid(tmp_path):
    """4 single-device processes forming one (data 2, model 2) mesh — the
    deployment topology (Megatron TP on the inner axis, DP across): each
    data-group feeds its own batch half (replicated over its model pair),
    every rank reports the identical global loss, one checkpoint."""
    import json

    ckpt_dir = tmp_path / "ckpt"
    outs = _run_workers(tmp_path, _GRID_WORKER, 4, extra_args=[ckpt_dir])
    rows = {r: (int(p.split(" ", 1)[0]), json.loads(p.split(" ", 1)[1]))
            for r, p in _parse(outs, "ROWS").items()}
    metrics = _parse(outs, "METRICS")

    assert set(rows) == {0, 1, 2, 3}
    # 8-row batch over data=2: each data group holds a 4-row half,
    # replicated across its model pair; halves are disjoint.
    assert all(rows[r][0] == 4 for r in rows)
    assert rows[0][1] == rows[1][1]
    assert rows[2][1] == rows[3][1]
    assert rows[0][1] != rows[2][1]
    # One identical global loss on every rank; exactly one checkpoint.
    assert set(metrics) == {0, 1, 2, 3}
    assert len(set(metrics.values())) == 1
    files = sorted(p.name for p in ckpt_dir.iterdir())
    assert files.count("checkpoint.msgpack") == 1, files


_DCN_WORKER = textwrap.dedent(
    """
    import os, sys, json
    pid = sys.argv[1]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    os.environ["PTD_TPU_COORDINATOR"] = "127.0.0.1:%(port)d"
    os.environ["PTD_TPU_NUM_PROCESSES"] = "2"
    os.environ["PTD_TPU_PROCESS_ID"] = pid
    sys.path.insert(0, %(repo)r)
    import jax
    jax.config.update("jax_platforms", "cpu")
    # match conftest: the in-process reference below runs with the
    # partitionable threefry (dropout/init key semantics follow it)
    jax.config.update("jax_threefry_partitionable", True)
    from pytorch_distributed_tpu.parallel import initialize
    from pytorch_distributed_tpu.parallel.mesh import (
        MeshSpec, build_hybrid_mesh,
    )
    ctx = initialize()
    assert ctx.process_count == 2
    # 2 processes x 2 local devices: process = DCN granule, so the 'data'
    # axis decomposes hierarchically (in-process ICI pair, cross-process
    # DCN hop) — the multi-slice layout running LIVE.
    mesh = build_hybrid_mesh(MeshSpec(("data",), (4,)), granule="process")
    order = [int(d.process_index) for d in mesh.devices.ravel()]
    print("ORDER", pid, json.dumps(order), flush=True)

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from pytorch_distributed_tpu import models
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState
    from pytorch_distributed_tpu.train.steps import make_train_step

    model = models.create_model("resnet18", num_classes=4)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 32, 32, 3)), train=False)
    state = TrainState.create(variables, sgd_init(variables["params"]))
    step = make_train_step(model, mesh)
    rng = np.random.default_rng(0)
    B = 8
    imgs = rng.normal(size=(B, 32, 32, 3)).astype(np.float32)
    labels = rng.integers(0, 4, size=B).astype(np.int32)
    sh = NamedSharding(mesh, P("data"))
    gm = sh.devices_indices_map((B, 32, 32, 3))
    me = int(jax.process_index())
    spans = sorted(
        (s[0].start or 0, B if s[0].stop is None else s[0].stop)
        for d, s in gm.items() if d.process_index == me
    )
    lo, hi = spans[0][0], spans[-1][1]
    print("SPAN", pid, json.dumps([lo, hi]), flush=True)
    local = {
        "images": imgs[lo:hi],
        "labels": labels[lo:hi],
        "weights": np.ones(hi - lo, np.float32),
    }
    batch = {
        k: jax.make_array_from_process_local_data(sh, v)
        for k, v in local.items()
    }
    lr = jnp.float32(0.1)
    losses = []
    for _ in range(2):
        state, metrics = step(state, batch, lr)
        losses.append(round(float(metrics["loss"]), 5))
    print("LOSSES", pid, json.dumps(losses), flush=True)
    """
)


def test_two_process_hybrid_dcn_dp_step(tmp_path):
    """The DCN axis running LIVE (VERDICT r3 item 7): 2 processes x 2 local
    devices form the hybrid (process-granule) data mesh, run the GSPMD DP
    train step end-to-end for 2 steps, and the losses match a replicated
    1-device oracle at the same seed — previously the hybrid mesh was only
    placement-tested with fake devices."""
    import json

    import jax
    import jax.numpy as jnp
    import numpy as np

    outs = _run_workers(tmp_path, _DCN_WORKER, 2)
    orders = {r: json.loads(p) for r, p in _parse(outs, "ORDER").items()}
    spans = {r: json.loads(p) for r, p in _parse(outs, "SPAN").items()}
    losses = {r: json.loads(p) for r, p in _parse(outs, "LOSSES").items()}

    # Hierarchical (slice-major) device order: process 0's ICI pair first.
    assert orders[0] == orders[1] == [0, 0, 1, 1]
    # Contiguous, disjoint per-process row shards covering the batch.
    assert spans[0] == [0, 4] and spans[1] == [4, 8]
    assert losses[0] == losses[1]

    # Replicated oracle: same model/seed/batch on a 1-device mesh in this
    # process (GSPMD global-batch BN stats make the math identical).
    from pytorch_distributed_tpu import models
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState
    from pytorch_distributed_tpu.train.steps import make_train_step

    mesh1 = build_mesh(MeshSpec(("data",), (1,)), jax.devices()[:1])
    model = models.create_model("resnet18", num_classes=4)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 32, 32, 3)), train=False)
    state = TrainState.create(variables, sgd_init(variables["params"]))
    step = make_train_step(model, mesh1)
    rng = np.random.default_rng(0)
    imgs = rng.normal(size=(8, 32, 32, 3)).astype(np.float32)
    labels = rng.integers(0, 4, size=8).astype(np.int32)
    batch = {"images": jnp.asarray(imgs), "labels": jnp.asarray(labels),
             "weights": jnp.ones(8, jnp.float32)}
    want = []
    for _ in range(2):
        state, metrics = step(state, batch, jnp.float32(0.1))
        want.append(float(metrics["loss"]))
    np.testing.assert_allclose(losses[0], want, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("tp", [1, 2])
def test_two_process_lm_pretrain(tmp_path, tp):
    """2-process LM twin of the image Trainer test (VERDICT r2 item 8):
    DP (tp=1) — disjoint halves of each global batch, identical all-reduced
    metrics, one checkpoint; TP (tp=2) — a cross-process model axis where
    both ranks feed the replicated batch."""
    import json

    ckpt_dir = tmp_path / "ckpt"
    outs = _run_workers(tmp_path, _LM_WORKER, 2, extra_args=[ckpt_dir, tp])
    rows = {r: (int(p.split(" ", 1)[0]), json.loads(p.split(" ", 1)[1]))
            for r, p in _parse(outs, "ROWS").items()}
    metrics = _parse(outs, "METRICS")

    assert set(rows) == {0, 1}
    if tp == 1:
        # Disjoint contiguous halves of the global batch (8 = 4 + 4).
        assert rows[0][0] == rows[1][0] == 4
        assert rows[0][1] != rows[1][1]
    else:
        # Replicated over the model axis: both ranks feed the full batch.
        assert rows[0][0] == rows[1][0] == 8
        assert rows[0][1] == rows[1][1]

    # Identical global metrics on both ranks (in-graph reductions).
    assert set(metrics) == {0, 1}
    assert metrics[0] == metrics[1]

    # Exactly one rank wrote the checkpoint.
    files = sorted(p.name for p in ckpt_dir.iterdir())
    assert files.count("checkpoint.msgpack") == 1, files

    # Cross-process heartbeats: both ranks beat into the shared dir, both
    # finished at the same step, nobody flagged (obs/heartbeat.py on the
    # LIVE multi-process mesh; straggler flagging itself is unit-tested in
    # tests/test_obs.py).
    from pytorch_distributed_tpu.obs import find_stragglers, read_heartbeats

    beats = read_heartbeats(str(ckpt_dir) + "_hb")
    assert set(beats) == {0, 1}
    assert beats[0]["step"] == beats[1]["step"] == 7  # fit(8) → last step 7
    assert find_stragglers(beats, max_step_lag=0, max_age_s=1e9) == {}


_TP_GENERATE_WORKER = textwrap.dedent(
    """
    import os, sys, json
    pid = sys.argv[1]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    os.environ["PTD_TPU_COORDINATOR"] = "127.0.0.1:%(port)d"
    os.environ["PTD_TPU_NUM_PROCESSES"] = "2"
    os.environ["PTD_TPU_PROCESS_ID"] = pid
    sys.path.insert(0, %(repo)r)
    import jax
    jax.config.update("jax_platforms", "cpu")
    from pytorch_distributed_tpu.parallel import initialize
    ctx = initialize()
    assert ctx.process_count == 2

    import numpy as np
    import jax.numpy as jnp
    from pytorch_distributed_tpu.models.generate import (
        generate, tp_generate,
    )
    from pytorch_distributed_tpu.models.transformer import TransformerLM
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh

    CFG = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=2)
    model = TransformerLM(**CFG)
    params = model.init(jax.random.PRNGKey(3),
                        jnp.zeros((1, 16), jnp.int32))["params"]
    rng = np.random.default_rng(3)
    prompt = jnp.asarray(rng.integers(0, 64, size=(2, 5)).astype(np.int32))

    # Cross-process model axis: each process holds one member of the TP
    # pair — the multi-host serving layout, LIVE (params identical on
    # both ranks, device_put places only the addressable half).
    mesh = build_mesh(MeshSpec(("model",), (2,)), jax.devices())
    got = tp_generate(params, prompt, 6, mesh=mesh, **CFG)
    # Replicate the (tiny) token array so every process holds all shards.
    from jax.sharding import NamedSharding, PartitionSpec as P
    rep = jax.jit(lambda x: x,
                  out_shardings=NamedSharding(mesh, P()))(got)
    toks = np.asarray(rep)
    # oracle: the same decode on this rank's local device alone
    want = np.asarray(generate(params, prompt, 6, **CFG))
    print("TOKENS", pid, json.dumps(toks.reshape(-1).tolist()), flush=True)
    print("ORACLE", pid, json.dumps(want.reshape(-1).tolist()), flush=True)
    """
)


def test_two_process_tp_generate(tmp_path):
    """Model-parallel decode with the TP pair split ACROSS processes:
    both ranks run one global program and produce the single-device
    oracle's greedy stream."""
    import json

    outs = _run_workers(tmp_path, _TP_GENERATE_WORKER, 2)
    toks = _parse(outs, "TOKENS")
    oracle = _parse(outs, "ORACLE")
    assert set(toks) == {0, 1}
    assert toks[0] == toks[1]
    assert json.loads(toks[0]) == json.loads(oracle[0]) == json.loads(
        oracle[1])
