"""Live multi-process bootstrap: two real processes rendezvous through the
PTD_TPU_* env contract (the reference's tcp://127.0.0.1:23456 analogue,
multiprocessing_distributed.py:132-135), form one 2-device global mesh, and
agree on a cross-process collective."""

import os
import subprocess
import sys
import textwrap

import pytest

_WORKER = textwrap.dedent(
    """
    import os, sys
    pid = sys.argv[1]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    os.environ["PTD_TPU_COORDINATOR"] = "127.0.0.1:%(port)d"
    os.environ["PTD_TPU_NUM_PROCESSES"] = "2"
    os.environ["PTD_TPU_PROCESS_ID"] = pid
    sys.path.insert(0, %(repo)r)
    import jax
    jax.config.update("jax_platforms", "cpu")
    from pytorch_distributed_tpu.parallel import initialize, data_parallel_mesh
    ctx = initialize()
    assert ctx.process_count == 2
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = data_parallel_mesh()
    local = np.full((2, 4), float(ctx.process_index), np.float32)
    arr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("data")), local)
    total = jax.jit(lambda x: jnp.sum(x),
                    out_shardings=NamedSharding(mesh, P()))(arr)
    print(f"RESULT {ctx.process_index} {float(total)}", flush=True)
    """
)


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_rendezvous_and_collective(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "worker.py"
    script.write_text(_WORKER % {"port": _free_port(), "repo": repo})
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PTD_TPU", "JAX_", "XLA_"))}
    procs = [
        subprocess.Popen([sys.executable, str(script), str(i)],
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True, env=env)
        for i in (0, 1)
    ]
    try:
        outs = [p.communicate(timeout=300)[0] for p in procs]
    finally:
        for p in procs:  # no orphaned workers holding the port on timeout
            if p.poll() is None:
                p.kill()
    for i, out in enumerate(outs):
        assert procs[i].returncode == 0, out
        # 2x4 zeros from proc 0 + 2x4 ones from proc 1 ⇒ global sum 8.
        assert f"RESULT {i} 8.0" in out, out
