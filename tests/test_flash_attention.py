"""Pallas flash attention vs dense oracle (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.ops.flash_attention import flash_attention
from pytorch_distributed_tpu.parallel.ring import dense_attention


def _qkv(B=2, L=128, H=2, D=64, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(B, L, H, D)).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_dense(causal):
    q, k, v = _qkv()
    want = dense_attention(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal, 64, 64, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_multiblock_accumulation():
    # L=256 with 64-blocks: 4x4 block grid exercises the online-softmax
    # correction across many steps.
    q, k, v = _qkv(L=256)
    want = dense_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, True, 64, 64, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_gradients_match_dense(causal):
    q, k, v = _qkv(L=64, H=1, D=32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal, 32, 32, True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_flash_bf16():
    q, k, v = _qkv()
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    got = flash_attention(qb, kb, vb, True, 64, 64, True)
    assert got.dtype == jnp.bfloat16
    want = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got, dtype=np.float32),
                               np.asarray(want), rtol=5e-2, atol=5e-2)


def test_flash_rejects_indivisible_length():
    q, k, v = _qkv(L=96)
    with pytest.raises(AssertionError, match="must divide"):
        flash_attention(q, k, v, True, 64, 64, True)


@pytest.mark.parametrize("bwd_impl", ["pallas", "xla"])
@pytest.mark.parametrize("causal", [True, False])
def test_flash_bwd_impls_match_dense_multiblock(causal, bwd_impl):
    """Both backward implementations, multi-block grid (the Pallas dq and
    dk/dv kernels accumulate across 4x4 blocks here)."""
    q, k, v = _qkv(L=128, H=2, D=32, seed=3)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal, 32, 32, True, bwd_impl) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4, err_msg=name)


def test_flash_bwd_pallas_matches_xla_bf16():
    q, k, v = _qkv(L=128, H=1, D=64, seed=4)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))

    def loss(impl):
        def f(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, True, 64, 64, True, impl)
                .astype(jnp.float32) ** 2)
        return jax.grad(f, argnums=(0, 1, 2))(qb, kb, vb)

    gp = loss("pallas")
    gx = loss("xla")
    for a, b, name in zip(gp, gx, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=0.05, atol=0.05, err_msg=name)
