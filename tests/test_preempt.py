"""Preemption guard: SIGTERM → checkpoint at a safe boundary → resumable.

Reference gap being upgraded: the reference's fault-tolerance story is a
manual --start-epoch restart (reference distributed.py:48-52, SURVEY §5.3);
here preemption is detected, the run checkpoints itself at the exact step
(ft/), and resume continues mid-epoch.  The subprocess tests (slow) drive
real SIGTERM/SIGKILL through the chaos injectors: single-process
kill-and-resume parity for the image Trainer, and a live 2-process mesh
where one rank is SIGKILLed and the job restarts from the --save-steps
checkpoint.
"""

import os
import signal
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from pytorch_distributed_tpu.ft import ChaosSchedule, SignalAt
from pytorch_distributed_tpu.train.config import Config
from pytorch_distributed_tpu.train.trainer import Trainer
from pytorch_distributed_tpu.utils.preempt import PreemptionGuard

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_guard_flags_on_signal_and_chains_previous_handler():
    hits = []
    prev = signal.signal(signal.SIGUSR1, lambda s, f: hits.append(s))
    try:
        guard = PreemptionGuard(signals=(signal.SIGUSR1,)).install()
        assert not guard.triggered
        os.kill(os.getpid(), signal.SIGUSR1)
        assert guard.triggered
        assert hits == [signal.SIGUSR1]  # previous handler still ran
        guard.uninstall()
        assert signal.getsignal(signal.SIGUSR1) is not guard._handler
    finally:
        signal.signal(signal.SIGUSR1, prev)


def _cfg(tmp_path, **kw):
    base = dict(
        arch="resnet18", batch_size=16, epochs=3, lr=0.1, print_freq=100,
        synthetic=True, synthetic_length=48, image_size=32, num_classes=8,
        seed=0, checkpoint_dir=str(tmp_path), workers=2,
    )
    base.update(kw)
    return Config(**base)


def test_trainer_checkpoints_and_exits_on_preemption(tmp_path, capsys):
    guard = PreemptionGuard(signals=(signal.SIGUSR1,)).install()
    try:
        trainer = Trainer(_cfg(tmp_path), preempt=guard)
        guard.trigger()  # preempted before epoch 0 completes
        trainer.fit()
        out = capsys.readouterr().out
        assert "preemption signal" in out
        assert "* Acc@1" not in out  # exited before validate
        from pytorch_distributed_tpu.train.checkpoint import load_checkpoint

        _, meta = load_checkpoint(
            str(tmp_path / "checkpoint.msgpack"), trainer.state)
        # The epoch was incomplete: checkpoint records epoch-1 so resume
        # reruns it from the start.
        assert meta["epoch"] == -1

        cfg2 = _cfg(tmp_path, resume=str(tmp_path / "checkpoint.msgpack"),
                    epochs=1)
        t2 = Trainer(cfg2)
        assert cfg2.start_epoch == 0  # resumes by rerunning epoch 0
        t2.fit()  # checkpointed state is loadable and completes training
        assert "* Acc@1" in capsys.readouterr().out
    finally:
        guard.uninstall()


def test_trainer_mid_epoch_preemption_resumes_at_exact_step(tmp_path, capsys):
    """Step-granular preemption (ft/): a signal mid-epoch checkpoints the
    exact completed step; --resume restarts the SAME epoch at that step
    (no rerun) and the finished run matches an uninterrupted one."""
    from pytorch_distributed_tpu.train.checkpoint import (
        CHECKPOINT_NAME,
        load_checkpoint,
    )

    import jax

    # Reference: one uninterrupted epoch (4 steps at batch 16 / len 64).
    ref_dir = tmp_path / "ref"
    ref = Trainer(_cfg(ref_dir, epochs=1, synthetic_length=64,
                       checkpoint_dir=str(ref_dir)))
    ref.fit()
    ref_params = jax.device_get(ref.state.params)

    # Preempted: SIGUSR1 fired by the chaos injector at step 1; the
    # print_freq=1 poll catches it at step 2 → checkpoint with ft step 2.
    run_dir = tmp_path / "run"
    guard = PreemptionGuard(signals=(signal.SIGUSR1,)).install()
    try:
        t1 = Trainer(_cfg(run_dir, epochs=1, synthetic_length=64,
                          print_freq=1, checkpoint_dir=str(run_dir)),
                     preempt=guard,
                     chaos=ChaosSchedule(SignalAt(1, signal.SIGUSR1)))
        t1.fit()
    finally:
        guard.uninstall()
    out = capsys.readouterr().out
    assert "preemption signal" in out
    ckpt = str(run_dir / CHECKPOINT_NAME)
    _, meta = load_checkpoint(ckpt, t1.state)
    assert meta["epoch"] == 0
    assert 0 < meta["ft"]["step"] < 4  # mid-epoch, not a boundary save

    cfg2 = _cfg(run_dir, epochs=1, synthetic_length=64, resume=ckpt,
                checkpoint_dir=str(run_dir))
    t2 = Trainer(cfg2)
    assert cfg2.start_epoch == 0            # same epoch ...
    assert t2._resume_step == meta["ft"]["step"]  # ... exact step offset
    t2.fit()
    for a, b in zip(jax.tree_util.tree_leaves(ref_params),
                    jax.tree_util.tree_leaves(
                        jax.device_get(t2.state.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_lm_trainer_preempts_checkpoints_and_resumes(tmp_path, capsys):
    """The LMTrainer preemption path (previously only the image Trainer's
    guard was exercised): signal mid-run → stop at the step boundary →
    end-of-fit checkpoint carries the exact step → resume continues."""
    import jax

    from pytorch_distributed_tpu.models.transformer import TransformerLM
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
    from pytorch_distributed_tpu.train.checkpoint import (
        CHECKPOINT_NAME,
        load_checkpoint,
    )
    from pytorch_distributed_tpu.train.lm import (
        LMTrainer,
        SyntheticTokenDataset,
    )

    mesh = build_mesh(MeshSpec(("data",), (jax.device_count(),)))
    model = TransformerLM(vocab_size=32, d_model=32, n_heads=2, n_layers=1)
    ds = SyntheticTokenDataset(32, 16, 32)
    d = str(tmp_path / "ckpt")
    guard = PreemptionGuard(signals=(signal.SIGUSR1,)).install()
    try:
        with mesh:
            t = LMTrainer(model, mesh, ds, batch_size=8, lr=0.05,
                          eval_dataset=None, checkpoint_dir=d,
                          preempt=guard,
                          chaos=ChaosSchedule(SignalAt(3, signal.SIGUSR1)))
            t.fit(8, print_freq=1)
    finally:
        guard.uninstall()
    out = capsys.readouterr().out
    assert "preemption signal: stopping at step" in out
    stop = int(t.state.step)
    assert 0 < stop < 8
    ckpt = os.path.join(d, CHECKPOINT_NAME)
    _, meta = load_checkpoint(ckpt, t.state)
    assert meta["ft"]["global_step"] == stop
    with mesh:
        t2 = LMTrainer(model, mesh, ds, batch_size=8, lr=0.05,
                       eval_dataset=None, checkpoint_dir=d, resume=ckpt)
        assert t2._start_step == stop
        final = t2.fit(8, print_freq=4)
    assert np.isfinite(final)


# --------------------------------------------------------- subprocess e2e
_IMG_WORKER = textwrap.dedent(
    """
    import os, sys
    ckpt = sys.argv[1]; mode = sys.argv[2]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    sys.path.insert(0, %(repo)r)
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_threefry_partitionable", True)
    import numpy as np
    import signal as _sig
    from pytorch_distributed_tpu.ft import ChaosSchedule, SignalAt
    from pytorch_distributed_tpu.train.config import Config
    from pytorch_distributed_tpu.train.trainer import Trainer
    cfg = Config(arch="resnet18", batch_size=16, epochs=2, lr=0.1,
                 print_freq=1, synthetic=True, synthetic_length=64,
                 image_size=32, num_classes=8, seed=0, workers=2,
                 checkpoint_dir=ckpt, save_steps=2,
                 resume=(os.path.join(ckpt, "checkpoint.msgpack")
                         if mode == "resume" else None))
    # mode "kill": a REAL SIGTERM mid-epoch-0 (the pod-reclaim signal);
    # fit()'s default guard traps it, checkpoints the exact step, exits 0.
    chaos = (ChaosSchedule(SignalAt(1, _sig.SIGTERM))
             if mode == "kill" else None)
    t = Trainer(cfg, chaos=chaos)
    t.fit()
    leaves = jax.tree_util.tree_leaves(jax.device_get(t.state.params))
    pn = float(np.sqrt(sum(
        float(np.sum(np.square(l.astype(np.float64)))) for l in leaves)))
    print("PNORM", f"{pn:.10e}", flush=True)
    print("GSTEP", int(t.state.step), flush=True)
    """
)


def _run_one(script_path, args, timeout=560):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PTD_TPU", "JAX_", "XLA_"))}
    return subprocess.run(
        [sys.executable, str(script_path)] + [str(a) for a in args],
        capture_output=True, text=True, timeout=timeout, env=env)


def _grab(out, key):
    for line in out.splitlines():
        if line.startswith(key + " "):
            return line.split(" ", 1)[1]
    raise AssertionError(f"{key!r} not found in:\n{out}")


@pytest.mark.slow
def test_sigterm_kill_and_resume_parity_subprocess(tmp_path):
    """Acceptance criterion 3, end to end with a real SIGTERM: run A
    trains 2 epochs uninterrupted; run B receives SIGTERM mid-epoch-0
    (chaos injector), checkpoints at the exact step, and exits; run C
    resumes and finishes.  C's final parameter norm matches A's."""
    script = tmp_path / "img_worker.py"
    script.write_text(_IMG_WORKER % {"repo": REPO})
    full = _run_one(script, [tmp_path / "a", "full"])
    assert full.returncode == 0, full.stdout + full.stderr
    killed = _run_one(script, [tmp_path / "b", "kill"])
    assert killed.returncode == 0, killed.stdout + killed.stderr
    assert "preemption signal" in killed.stdout
    # Interrupted partway: fewer global steps than the full run.
    assert int(_grab(killed.stdout, "GSTEP")) < int(
        _grab(full.stdout, "GSTEP"))
    resumed = _run_one(script, [tmp_path / "b", "resume"])
    assert resumed.returncode == 0, resumed.stdout + resumed.stderr
    assert "=> resumed" in resumed.stdout
    assert int(_grab(resumed.stdout, "GSTEP")) == int(
        _grab(full.stdout, "GSTEP"))
    np.testing.assert_allclose(
        float(_grab(resumed.stdout, "PNORM")),
        float(_grab(full.stdout, "PNORM")), rtol=1e-6)


_FT_LM_WORKER = textwrap.dedent(
    """
    import os, sys
    pid = sys.argv[1]; ckpt = sys.argv[2]; mode = sys.argv[3]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    os.environ["PTD_TPU_COORDINATOR"] = "127.0.0.1:%(port)d"
    os.environ["PTD_TPU_NUM_PROCESSES"] = "2"
    os.environ["PTD_TPU_PROCESS_ID"] = pid
    sys.path.insert(0, %(repo)r)
    import jax
    jax.config.update("jax_platforms", "cpu")
    from pytorch_distributed_tpu.parallel import (
        MeshSpec, build_mesh, initialize,
    )
    ctx = initialize()
    from pytorch_distributed_tpu.ft import ChaosSchedule, KillAt
    from pytorch_distributed_tpu.models.transformer import TransformerLM
    from pytorch_distributed_tpu.train.lm import (
        LMTrainer, SyntheticTokenDataset,
    )
    mesh = build_mesh(MeshSpec(("data",), (2,)))
    model = TransformerLM(vocab_size=32, d_model=32, n_heads=2, n_layers=1)
    ds = SyntheticTokenDataset(32, 16, 32)
    # mode "kill": rank 1 is SIGKILLed at the top of step 4 — no grace,
    # no handler; only the --save-steps cadence checkpoints survive (the
    # newest one saved at the end of step 3, i.e. completed step 4).
    chaos = ChaosSchedule(KillAt(4, rank=1)) if mode == "kill" else None
    resume = (os.path.join(ckpt, "checkpoint.msgpack")
              if mode == "resume" else None)
    with mesh:
        t = LMTrainer(model, mesh, ds, batch_size=8, lr=0.05,
                      is_primary=ctx.is_primary, checkpoint_dir=ckpt,
                      eval_dataset=None, save_steps=2, resume=resume,
                      chaos=chaos)
        print("START", ctx.process_index, t._start_step, flush=True)
        final = t.fit(8, print_freq=4)
    print("DONE", ctx.process_index, f"{final:.6f}", flush=True)
    """
)


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_pair(script, ckpt, mode):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PTD_TPU", "JAX_", "XLA_"))}
    return [
        subprocess.Popen(
            [sys.executable, str(script), str(i), str(ckpt), mode],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        for i in range(2)
    ]


@pytest.mark.slow
def test_rank_sigkill_then_restart_resumes_from_save_steps(tmp_path):
    """The dead-rank drill on a LIVE 2-process mesh (acceptance: failures
    are routine events): rank 1 is SIGKILLed mid-run; the job cannot
    continue (collectives need every rank), but the --save-steps cadence
    checkpoint survives, and restarting BOTH ranks with --resume picks up
    at that step and completes — step continuity proven end to end."""
    ckpt = tmp_path / "ckpt"
    script = tmp_path / "ft_lm_worker.py"

    # Phase 1: rank 1 dies by SIGKILL at step 4 (after the step-2 save).
    script.write_text(_FT_LM_WORKER % {"port": _free_port(), "repo": REPO})
    procs = _spawn_pair(script, ckpt, "kill")
    try:
        out1 = procs[1].communicate(timeout=540)[0]
        assert procs[1].returncode == -signal.SIGKILL, out1
        # Rank 0 is now blocked in (or erroring out of) a collective whose
        # peer is gone — exactly the real-world failure; reap it.
        try:
            procs[0].communicate(timeout=30)
        except subprocess.TimeoutExpired:
            pass
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    from pytorch_distributed_tpu.train.checkpoint import (
        CHECKPOINT_NAME,
        load_checkpoint,
    )

    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.models.transformer import TransformerLM
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState

    model = TransformerLM(vocab_size=32, d_model=32, n_heads=2, n_layers=1)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 16), jnp.int32))["params"]
    template = TrainState.create({"params": params}, sgd_init(params))
    _, meta = load_checkpoint(str(ckpt / CHECKPOINT_NAME), template)
    # The newest surviving cadence save: 4 completed steps (written at the
    # end of step index 3, just before the kill at the top of step 4).
    assert meta["ft"]["global_step"] == 4

    # Phase 2: restart the whole job (fresh rendezvous) with --resume.
    script.write_text(_FT_LM_WORKER % {"port": _free_port(), "repo": REPO})
    procs = _spawn_pair(script, ckpt, "resume")
    try:
        outs = [p.communicate(timeout=540)[0] for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for i, out in enumerate(outs):
        assert procs[i].returncode == 0, out
    starts = {int(ln.split()[1]): int(ln.split()[2])
              for out in outs for ln in out.splitlines()
              if ln.startswith("START ")}
    dones = {int(ln.split()[1]): ln.split()[2]
             for out in outs for ln in out.splitlines()
             if ln.startswith("DONE ")}
    assert starts == {0: 4, 1: 4}       # both ranks resumed at step 4
    assert set(dones) == {0, 1}
    assert dones[0] == dones[1]         # identical all-reduced final loss
