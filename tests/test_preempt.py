"""Preemption guard: SIGTERM → checkpoint at a safe boundary → resumable.

Reference gap being upgraded: the reference's fault-tolerance story is a
manual --start-epoch restart (reference distributed.py:48-52, SURVEY §5.3);
here preemption is detected and the run checkpoints itself.
"""

import os
import signal

from pytorch_distributed_tpu.train.config import Config
from pytorch_distributed_tpu.train.trainer import Trainer
from pytorch_distributed_tpu.utils.preempt import PreemptionGuard


def test_guard_flags_on_signal_and_chains_previous_handler():
    hits = []
    prev = signal.signal(signal.SIGUSR1, lambda s, f: hits.append(s))
    try:
        guard = PreemptionGuard(signals=(signal.SIGUSR1,)).install()
        assert not guard.triggered
        os.kill(os.getpid(), signal.SIGUSR1)
        assert guard.triggered
        assert hits == [signal.SIGUSR1]  # previous handler still ran
        guard.uninstall()
        assert signal.getsignal(signal.SIGUSR1) is not guard._handler
    finally:
        signal.signal(signal.SIGUSR1, prev)


def _cfg(tmp_path, **kw):
    base = dict(
        arch="resnet18", batch_size=16, epochs=3, lr=0.1, print_freq=100,
        synthetic=True, synthetic_length=48, image_size=32, num_classes=8,
        seed=0, checkpoint_dir=str(tmp_path), workers=2,
    )
    base.update(kw)
    return Config(**base)


def test_trainer_checkpoints_and_exits_on_preemption(tmp_path, capsys):
    guard = PreemptionGuard(signals=(signal.SIGUSR1,)).install()
    try:
        trainer = Trainer(_cfg(tmp_path), preempt=guard)
        guard.trigger()  # preempted before epoch 0 completes
        trainer.fit()
        out = capsys.readouterr().out
        assert "preemption signal" in out
        assert "* Acc@1" not in out  # exited before validate
        from pytorch_distributed_tpu.train.checkpoint import load_checkpoint

        _, meta = load_checkpoint(
            str(tmp_path / "checkpoint.msgpack"), trainer.state)
        # The epoch was incomplete: checkpoint records epoch-1 so resume
        # reruns it from the start.
        assert meta["epoch"] == -1

        cfg2 = _cfg(tmp_path, resume=str(tmp_path / "checkpoint.msgpack"),
                    epochs=1)
        t2 = Trainer(cfg2)
        assert cfg2.start_epoch == 0  # resumes by rerunning epoch 0
        t2.fit()  # checkpointed state is loadable and completes training
        assert "* Acc@1" in capsys.readouterr().out
    finally:
        guard.uninstall()
