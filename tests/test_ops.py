"""Unit tests for ops: accuracy (vs torch-semantics oracle) and cross-entropy."""

import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.ops import accuracy, cross_entropy, topk_correct


def _np_topk_accuracy(logits, labels, k):
    """Oracle mirroring reference accuracy() (distributed.py:381-395)."""
    topk_idx = np.argsort(-logits, axis=-1)[:, :k]
    correct = (topk_idx == labels[:, None]).any(axis=-1)
    return correct.mean() * 100.0


@pytest.mark.parametrize("k", [1, 5])
def test_accuracy_matches_numpy_oracle(k):
    rng = np.random.default_rng(0)
    logits = rng.normal(size=(64, 100)).astype(np.float32)
    labels = rng.integers(0, 100, size=64).astype(np.int32)
    (got,) = accuracy(jnp.asarray(logits), jnp.asarray(labels), topk=(k,))
    want = _np_topk_accuracy(logits, labels, k)
    np.testing.assert_allclose(float(got), want, rtol=1e-6)


def test_accuracy_topk_pair():
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(32, 10)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, 10, size=32).astype(np.int32))
    top1, top5 = accuracy(logits, labels, topk=(1, 5))
    assert 0.0 <= float(top1) <= float(top5) <= 100.0


def test_accuracy_weights_mask_padding():
    rng = np.random.default_rng(2)
    logits = rng.normal(size=(16, 10)).astype(np.float32)
    labels = rng.integers(0, 10, size=16).astype(np.int32)
    # Pad with garbage rows carrying weight 0.
    logits_p = np.concatenate([logits, rng.normal(size=(4, 10)).astype(np.float32)])
    labels_p = np.concatenate([labels, np.zeros(4, dtype=np.int32)])
    w = np.concatenate([np.ones(16, np.float32), np.zeros(4, np.float32)])
    (unpadded,) = accuracy(jnp.asarray(logits), jnp.asarray(labels), topk=(1,))
    (masked,) = accuracy(
        jnp.asarray(logits_p), jnp.asarray(labels_p), topk=(1,), weights=jnp.asarray(w)
    )
    np.testing.assert_allclose(float(masked), float(unpadded), rtol=1e-6)


def test_topk_correct_all_k_equals_one():
    logits = jnp.asarray(np.eye(8, dtype=np.float32) * 10.0)
    labels = jnp.arange(8, dtype=jnp.int32)
    assert float(topk_correct(logits, labels, 1).sum()) == 8.0


def test_cross_entropy_matches_torch():
    torch = pytest.importorskip("torch")
    rng = np.random.default_rng(3)
    logits = rng.normal(size=(32, 50)).astype(np.float32)
    labels = rng.integers(0, 50, size=32).astype(np.int64)
    want = torch.nn.functional.cross_entropy(
        torch.from_numpy(logits), torch.from_numpy(labels)
    ).item()
    got = float(cross_entropy(jnp.asarray(logits), jnp.asarray(labels.astype(np.int32))))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_cross_entropy_weighted_padding():
    rng = np.random.default_rng(4)
    logits = rng.normal(size=(8, 5)).astype(np.float32)
    labels = rng.integers(0, 5, size=8).astype(np.int32)
    base = float(cross_entropy(jnp.asarray(logits), jnp.asarray(labels)))
    logits_p = np.concatenate([logits, np.ones((2, 5), np.float32)])
    labels_p = np.concatenate([labels, np.zeros(2, np.int32)])
    w = np.concatenate([np.ones(8, np.float32), np.zeros(2, np.float32)])
    got = float(
        cross_entropy(jnp.asarray(logits_p), jnp.asarray(labels_p), weights=jnp.asarray(w))
    )
    np.testing.assert_allclose(got, base, rtol=1e-6)


def test_cross_entropy_bf16_logits_close_to_f32():
    rng = np.random.default_rng(5)
    logits = rng.normal(size=(64, 100)).astype(np.float32)
    labels = rng.integers(0, 100, size=64).astype(np.int32)
    f32 = float(cross_entropy(jnp.asarray(logits), jnp.asarray(labels)))
    bf16 = float(
        cross_entropy(jnp.asarray(logits, dtype=jnp.bfloat16), jnp.asarray(labels))
    )
    np.testing.assert_allclose(bf16, f32, rtol=2e-2)
