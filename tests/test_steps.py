"""DP-semantics tests on the simulated 8-device mesh.

The core correctness contracts (SURVEY.md §4 implication):
- sharded-batch gradient step ≡ single-device large-batch step
- GSPMD and explicit-shard_map steps agree
- bf16 wire compression only perturbs within tolerance
- metrics are global (all shards contribute)
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu import models
from pytorch_distributed_tpu.parallel import build_mesh, MeshSpec
from pytorch_distributed_tpu.train.optim import sgd_init
from pytorch_distributed_tpu.train.state import TrainState
from pytorch_distributed_tpu.train.steps import make_eval_step, make_train_step


def _setup(num_devices=8, image=32, classes=10, batch=16, seed=0):
    # Private compile, deliberately NOT on the shared lowering sweep:
    # resnet18 (BN) at 32x32 on the 8-way mesh has no recipe twin in
    # analysis.core.RECIPES (the matrix is the BN-free TinyMLP at 4-way).
    mesh = build_mesh(MeshSpec(("data",), (num_devices,)), jax.devices()[:num_devices])
    model = models.create_model("resnet18", num_classes=classes)
    rng = jax.random.PRNGKey(seed)
    variables = model.init(rng, jnp.zeros((1, image, image, 3)), train=False)
    state = TrainState.create(variables, sgd_init(variables["params"]))
    np_rng = np.random.default_rng(seed)
    batch_data = {
        "images": np_rng.normal(size=(batch, image, image, 3)).astype(np.float32),
        "labels": np_rng.integers(0, classes, size=batch).astype(np.int32),
        "weights": np.ones(batch, np.float32),
    }
    return mesh, model, state, batch_data


def _leaves_allclose(a, b, rtol, atol=1e-5):
    fa = jax.tree_util.tree_leaves(a)
    fb = jax.tree_util.tree_leaves(b)
    assert len(fa) == len(fb)
    for x, y in zip(fa, fb):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=rtol, atol=atol)


def test_sharded_step_matches_single_device():
    mesh8, model, state, batch = _setup()
    mesh1 = build_mesh(MeshSpec(("data",), (1,)), jax.devices()[:1])
    step8 = make_train_step(model, mesh8)
    step1 = make_train_step(model, mesh1)
    s8, m8 = step8(state, batch, jnp.float32(0.1))
    # state was donated; rebuild for the single-device run
    _, _, state2, _ = (None, None, *_setup()[2:3], None)
    s1, m1 = step1(state2, batch, jnp.float32(0.1))
    np.testing.assert_allclose(float(m8["loss"]), float(m1["loss"]), rtol=1e-4)
    np.testing.assert_allclose(float(m8["acc1"]), float(m1["acc1"]), atol=1e-4)
    _leaves_allclose(s8.params, s1.params, rtol=1e-4)


class _MLP(__import__("flax").linen.Module):
    """BN-free model: isolates collective plumbing from BN-semantics deltas."""

    classes: int = 10

    @__import__("flax").linen.compact
    def __call__(self, x, train: bool = True):
        import flax.linen as nn

        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(32)(x)
        x = nn.relu(x)
        return nn.Dense(self.classes)(x)


def _setup_mlp(num_devices=8, image=8, classes=10, batch=16, seed=0):
    # Still needed where the assertion depends on a shape the recipe
    # matrix doesn't carry (the padded-batch test re-steps at batch 8,
    # which would force a second compile of the shared twin anyway).
    mesh = build_mesh(MeshSpec(("data",), (num_devices,)), jax.devices()[:num_devices])
    model = _MLP(classes=classes)
    variables = model.init(jax.random.PRNGKey(seed), jnp.zeros((1, image, image, 3)))
    state = TrainState.create(variables, sgd_init(variables["params"]))
    np_rng = np.random.default_rng(seed)
    batch_data = {
        "images": np_rng.normal(size=(batch, image, image, 3)).astype(np.float32),
        "labels": np_rng.integers(0, classes, size=batch).astype(np.int32),
        "weights": np.ones(batch, np.float32),
    }
    return mesh, model, state, batch_data


def test_explicit_shard_map_matches_gspmd_without_bn(get_lowering):
    """With no BatchNorm the two gradient-sync formulations must agree.

    Rides the session-shared lowering sweep (ISSUE 13 S3): the BN-free
    recipe twins ``train_image_gspmd`` / ``train_image_explicit`` are
    already compiled once per session for the shardlint/ledger fences,
    so the semantics check re-executes those compiled steps on fresh
    (undonated) states instead of paying two private compiles.  The
    resnet18/BN tests below keep their private ``_setup`` compiles —
    their model and 8-way mesh are not in the recipe matrix."""
    from pytorch_distributed_tpu.analysis import core

    low_g = get_lowering("train_image_gspmd")
    low_e = get_lowering("train_image_explicit")
    before = get_lowering.compile_count()
    batch = core._image_batch()
    sg, mg = low_g.jitted(core._image_state(core._tiny_image_model()),
                          batch, jnp.float32(0.1))
    se, me = low_e.jitted(
        core._image_state(core._tiny_image_model(), explicit=True),
        batch, jnp.float32(0.1))
    np.testing.assert_allclose(float(mg["loss"]), float(me["loss"]), rtol=1e-5)
    np.testing.assert_allclose(float(mg["acc1"]), float(me["acc1"]), atol=1e-5)
    _leaves_allclose(sg.params, se.params, rtol=1e-5)
    # re-executing cached twins is free: zero new AOT compiles, and the
    # process-wide sweep stays inside its budget
    assert get_lowering.compile_count() == before
    assert get_lowering.compile_count() <= get_lowering.compile_budget()


def test_shard_map_bn_is_local_like_torch_ddp():
    """Documented delta: shard_map BN normalizes per shard (torch DDP parity),
    GSPMD BN is global (SyncBN).  Losses must *differ* on small shards."""
    mesh, model, state, batch = _setup()
    step_g = make_train_step(model, mesh)
    step_e = make_train_step(model, mesh, explicit_collectives=True)
    _, mg = step_g(state, batch, jnp.float32(0.1))
    _, _, state2, _ = _setup()
    _, me = step_e(state2, batch, jnp.float32(0.1))
    assert abs(float(mg["loss"]) - float(me["loss"])) > 1e-3


def test_bf16_wire_compression_close_to_f32():
    mesh, model, state, batch = _setup()
    step_f = make_train_step(model, mesh, explicit_collectives=True)
    step_w = make_train_step(model, mesh, explicit_collectives=True,
                             wire_dtype=jnp.bfloat16)
    sf, _ = step_f(state, batch, jnp.float32(0.1))
    _, _, state2, _ = _setup()
    sw, _ = step_w(state2, batch, jnp.float32(0.1))
    # bf16 has ~3 decimal digits; updates are lr-scaled so params stay close.
    _leaves_allclose(sf.params, sw.params, rtol=5e-2, atol=5e-3)


def test_padded_batch_excluded_from_loss_and_grads():
    """On a BN-free model, a zero-weighted pad half must leave loss, metrics,
    AND the parameter update identical to the unpadded half-batch.  (BN models
    avoid train-time padding entirely: the trainer drops the partial final
    train batch; eval uses running stats, so padding is exact there.)"""
    mesh, model, state, batch = _setup_mlp(batch=16)
    step = make_train_step(model, mesh)
    batch_padded = {
        "images": np.concatenate([batch["images"][:8],
                                  np.zeros_like(batch["images"][:8])]),
        "labels": np.concatenate([batch["labels"][:8], np.zeros(8, np.int32)]),
        "weights": np.concatenate([np.ones(8, np.float32), np.zeros(8, np.float32)]),
    }
    s_pad, m_pad = step(state, batch_padded, jnp.float32(0.1))

    mesh_b, model_b, state_b, _ = _setup_mlp(batch=16)
    batch_half = {
        "images": batch["images"][:8],
        "labels": batch["labels"][:8],
        "weights": np.ones(8, np.float32),
    }
    step_half = make_train_step(model_b, mesh_b)
    s_half, m_half = step_half(state_b, batch_half, jnp.float32(0.1))
    np.testing.assert_allclose(float(m_pad["loss"]), float(m_half["loss"]), rtol=1e-5)
    np.testing.assert_allclose(float(m_pad["acc1"]), float(m_half["acc1"]), atol=1e-5)
    _leaves_allclose(s_pad.params, s_half.params, rtol=1e-5)


def test_eval_step_returns_exact_sums():
    mesh, model, state, batch = _setup()
    ev = make_eval_step(model, mesh)
    batch["weights"][-3:] = 0.0
    sums = ev(state, batch)
    assert float(sums["count"]) == 13.0
    assert 0.0 <= float(sums["correct1"]) <= 13.0
    assert float(sums["correct1"]) <= float(sums["correct5"])


def test_train_step_increments_step_counter():
    mesh, model, state, batch = _setup()
    step = make_train_step(model, mesh)
    s1, _ = step(state, batch, jnp.float32(0.1))
    assert int(s1.step) == 1
    s2, _ = step(s1, batch, jnp.float32(0.1))
    assert int(s2.step) == 2
