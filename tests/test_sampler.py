"""Sharding-math tests for DistributedShardSampler (DistributedSampler parity)."""

import numpy as np
import pytest

from pytorch_distributed_tpu.data import DistributedShardSampler


def test_shards_are_disjoint_and_cover_dataset():
    world, n = 4, 103
    shards = []
    for r in range(world):
        s = DistributedShardSampler(n, num_replicas=world, rank=r, shuffle=True, seed=7)
        s.set_epoch(0)
        idx, valid = s.shard()
        assert len(idx) == s.num_samples == -(-n // world)
        shards.append(idx[valid.astype(bool)])
    all_valid = np.concatenate(shards)
    assert sorted(all_valid.tolist()) == list(range(n))


def test_padding_wraps_and_is_marked_invalid():
    n, world = 10, 4  # total_size 12, 2 pad slots
    total_valid = 0
    for r in range(world):
        s = DistributedShardSampler(n, num_replicas=world, rank=r, shuffle=False)
        idx, valid = s.shard()
        assert len(idx) == 3
        total_valid += int(valid.sum())
    assert total_valid == n


def test_set_epoch_reshuffles_deterministically():
    s = DistributedShardSampler(64, num_replicas=2, rank=0, shuffle=True, seed=1)
    s.set_epoch(0)
    e0 = s.shard()[0].copy()
    s.set_epoch(1)
    e1 = s.shard()[0].copy()
    assert not np.array_equal(e0, e1)
    s.set_epoch(0)
    np.testing.assert_array_equal(s.shard()[0], e0)


def test_all_ranks_agree_on_global_permutation():
    perms = []
    for r in range(4):
        s = DistributedShardSampler(50, num_replicas=4, rank=r, shuffle=True, seed=3)
        s.set_epoch(5)
        perms.append(s.global_indices()[0])
    for p in perms[1:]:
        np.testing.assert_array_equal(p, perms[0])


def test_matches_torch_distributed_sampler_partition():
    """Strided rank assignment identical to torch DistributedSampler (no shuffle)."""
    torch = pytest.importorskip("torch")
    from torch.utils.data.distributed import DistributedSampler

    class _DS(torch.utils.data.Dataset):
        def __len__(self):
            return 21

        def __getitem__(self, i):
            return i

    for r in range(3):
        ts = DistributedSampler(_DS(), num_replicas=3, rank=r, shuffle=False)
        want = list(iter(ts))
        ours = DistributedShardSampler(21, num_replicas=3, rank=r, shuffle=False)
        got = list(iter(ours))
        assert got == want


def test_drop_last():
    s = DistributedShardSampler(10, num_replicas=4, rank=0, shuffle=False, drop_last=True)
    idx, valid = s.shard()
    assert len(idx) == 2 and valid.all()
