"""HBM memory ledger + live-range watermark (obs/memory.py).

Layers under test:
- the **±10% parity fence** (the ISSUE-11 acceptance bar): for every
  recipe-matrix step the static watermark peak reconstructed from the
  compiled HLO text must land within ±10% of the compiler's own
  ``memory_analysis()`` ground truth — lowerings come off the
  session-shared ``get_lowering`` fixture, so this suite adds zero
  compiles beyond test_shardlint's sweep (and asserts exactly that via
  the process-wide compile counter);
- **ZeRO reclaim from the ledger alone**: the ``opt_state`` class peak
  of the replicated steps must be >= 3.5x the wus-sharded steps' —
  the ``--zero wus`` memory win reproduced without touching a live
  array shard;
- **fused-CE ordering**: the ledger must rank the three LM CE variants
  the same way the measured experiment (RESULTS_fused_ce_memory.json
  ``rows_dp``) does: fused+dp-sharded < fused+replicated < unfused;
- the **shardlint memory budget**: a planted oversized peak against the
  checked-in baseline must come back as an error-severity
  ``memory-budget`` finding (and an undershoot as info);
- the **obs_report --diff fence**: a planted per-step ``peak_hbm_bytes``
  regression at identical step time must exit 1;
- analytic model fences (obs/flops.py ``train_mem_peak`` /
  ``lm_train_mem_peak`` vs the ledger, ±15%);
- serialization: mem_ledger.json round-trip, the Perfetto counter track;
- heartbeat memory: ``beat(mem_bytes=...)`` round-trips through
  ``read_heartbeats`` and shows up in ``find_stragglers`` reasons;
- ``scripts/benchlib.bench_staleness`` aging (satellite: bench results
  age out with a WARN instead of silently going stale);
- ``scripts/obs_memory.py --selftest`` end to end (separate process,
  no jax import on that path).
"""

import json
import os
import subprocess
import sys

import jax
import pytest

from pytorch_distributed_tpu.analysis import core, report
from pytorch_distributed_tpu.obs import comms, flops, heartbeat, memory, timeline

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "scripts"))

import benchlib  # noqa: E402
import obs_report  # noqa: E402

BASELINE = os.path.join(ROOT, "pytorch_distributed_tpu", "analysis",
                        "baseline.json")


def _ledger(low):
    return memory.ledger_from_hlo_text(
        low.text, step=low.name, mesh_shape=low.mesh_shape,
        arg_classes=memory.arg_classes_of(low.args),
        measured_peak_bytes=comms.compiled_peak_bytes(low.compiled))


# ------------------------------------------------- parity fence (±10%)

@pytest.mark.parametrize("name", list(core.RECIPES))
def test_watermark_parity(get_lowering, name):
    """The acceptance fence: the static watermark peak vs the compiler's
    ``memory_analysis()``, within ±10% on every recipe step."""
    lg = _ledger(get_lowering(name))
    assert lg.peak_bytes > 0 and lg.n_instructions > 0
    assert lg.measured_peak_bytes > 0
    res = lg.residual_pct()
    assert res <= 10.0, (name, lg.peak_bytes, lg.measured_peak_bytes, res)
    # the watermark curve is internally consistent: monotone indices,
    # its max is the peak, and the peak index points into the schedule
    idxs = [i for i, _ in lg.watermark]
    assert idxs == sorted(idxs)
    assert max(b for _, b in lg.watermark) == lg.peak_bytes
    assert 0 <= lg.peak_index < lg.n_instructions
    # arguments+outputs never exceed the peak (they are resident there)
    assert lg.argument_bytes + lg.output_bytes - lg.donated_bytes \
        <= lg.peak_bytes


def test_top_buffers_attribution(get_lowering):
    """Attribution plumbing on a real lowering: top buffers carry class,
    phase, and shape; params/opt_state classes both appear at peak for
    the explicit image step."""
    lg = _ledger(get_lowering("train_image_explicit"))
    top = lg.top_buffers(16)
    assert top and all(b.bytes > 0 for b in top)
    assert top == sorted(top, key=lambda b: (-b.bytes, b.name))
    classes = {b.klass for b in top}
    assert "params" in classes and "opt_state" in classes, classes
    cp = lg.class_peaks()
    for k in ("params", "opt_state", "activations", "output"):
        assert cp.get(k, 0) > 0, cp
    # live_at(peak) sums to the watermark level at the peak
    live = lg.live_at(lg.peak_index)
    assert sum(b.bytes for b in live) == lg.peak_bytes


# --------------------------------------------- ZeRO reclaim (>= 3.5x)

@pytest.mark.parametrize("repl,zero", [
    ("train_image_explicit", "train_image_zero"),
    ("lm_train_dp", "train_lm_zero"),
])
def test_zero_opt_state_reclaim(get_lowering, repl, zero):
    """--zero wus reclaims the optimizer state: the ledger's opt_state
    class peak, read from the compiled HLO alone, shows the (N-1)/N
    shard reclaim (>= 3.5x on the 4-way mesh)."""
    lg_r = _ledger(get_lowering(repl))
    lg_z = _ledger(get_lowering(zero))
    opt_r = lg_r.class_peaks().get("opt_state", 0)
    opt_z = lg_z.class_peaks().get("opt_state", 0)
    assert opt_r > 0 and opt_z > 0
    ratio = opt_r / opt_z
    assert ratio >= 3.5, (repl, zero, opt_r, opt_z, ratio)
    # and the overall peak moves the right way too
    assert lg_z.peak_bytes < lg_r.peak_bytes


# -------------------------------------------- fused-CE peak ordering

def test_fused_ce_peak_ordering(get_lowering):
    """The ledger ranks the LM CE variants the way the measured
    experiment does (RESULTS_fused_ce_memory.json ``rows_dp``):
    fused+dp-sharded < fused+replicated, both below the unfused step."""
    with open(os.path.join(ROOT, "RESULTS_fused_ce_memory.json")) as f:
        rows = json.load(f)["rows_dp"]
    assert rows["fused_c8_dp"]["peak_mib"] \
        < rows["fused_c8_replicated"]["peak_mib"] \
        < rows["unfused"]["peak_mib"]

    lg_un = _ledger(get_lowering("lm_train_dp"))
    lg_rep = _ledger(get_lowering("lm_fused_ce_replicated"))
    lg_dp = _ledger(get_lowering("lm_fused_ce_dp"))
    # measured (memory_analysis) ordering matches the experiment exactly
    assert lg_dp.measured_peak_bytes < lg_rep.measured_peak_bytes \
        < lg_un.measured_peak_bytes, (
            lg_dp.measured_peak_bytes, lg_rep.measured_peak_bytes,
            lg_un.measured_peak_bytes)
    # the watermark resolves the fused dp-vs-replicated accumulator gap
    assert lg_dp.peak_bytes < lg_rep.peak_bytes


# --------------------------------------- shardlint memory budget fence

def test_planted_budget_regression_is_error(get_lowering):
    """A baseline whose pinned peak is 20% below the current lowering
    must produce an error-severity memory-budget finding; one 20% above
    reads as a stale-baseline info."""
    get_lowering("train_image_explicit")  # share the session compile
    rep = core.analyze_recipe("train_image_explicit")
    entry = report.load_baseline(BASELINE)["train_image_explicit"]
    peak = sum(rep.memory.values())
    assert peak > 0

    planted = dict(entry, peak_hbm_bytes=int(peak / 1.2))
    findings = report.diff_against_baseline(rep, planted)
    errs = [f for f in findings
            if f.kind == "memory-budget" and f.severity == "error"]
    assert errs, findings
    assert "peak HBM budget exceeded" in errs[0].message

    stale = dict(entry, peak_hbm_bytes=int(peak * 1.2))
    findings = report.diff_against_baseline(rep, stale)
    infos = [f for f in findings
             if f.kind == "memory-budget" and f.severity == "info"]
    assert infos and not [f for f in findings
                          if f.kind == "memory-budget"
                          and f.severity == "error"]

    # the checked-in baseline itself is clean within the 2% slack
    real = report.diff_against_baseline(rep, entry)
    assert not [f for f in real if f.kind == "memory-budget"
                and f.severity == "error"], real


def test_baseline_pins_peak_for_every_meshed_step():
    """Every meshed recipe's baseline entry carries the peak pin; a new
    recipe landing without one would silently skip the budget fence."""
    base = report.load_baseline(BASELINE)
    missing = [n for n, e in base.items()
               if "peak_hbm_bytes" not in e or e["peak_hbm_bytes"] <= 0]
    assert not missing, missing


# ------------------------------------------------ diff fence (exit 1)

def _write_run(path, peak_bytes):
    from pytorch_distributed_tpu.obs.metrics import MetricsLogger

    with MetricsLogger(path, flush_every=50) as log:
        for i in range(30):
            log.log_step(i, step_time=0.010, n_items=128, lr=0.1,
                         extra={"peak_hbm_bytes": float(peak_bytes),
                                "mem_residual_pct": 4.0})


def test_diff_exit_1_on_planted_peak_regression(tmp_path, capsys):
    """Identical step time, but the per-step compiled peak grew 25% —
    a layout change silently re-replicating state.  ``obs_report
    --diff`` must exit 1 on the peak_hbm_bytes row."""
    base = str(tmp_path / "base.jsonl")
    bad = str(tmp_path / "bad.jsonl")
    _write_run(base, peak_bytes=160_000)
    _write_run(bad, peak_bytes=200_000)
    rc = obs_report.main(["--diff", base, bad])
    out = capsys.readouterr().out
    assert rc == 1, out
    assert "REGRESS" in out and "peak_hbm_bytes" in out
    assert obs_report.main(["--diff", base, base]) == 0
    capsys.readouterr()
    rc_json = obs_report.main(["--diff", base, bad, "--format", "json"])
    js = json.loads(capsys.readouterr().out)
    assert rc_json == 1 and js["overall"] == "REGRESS"
    by_name = {r["metric"]: r for r in js["metrics"]}
    assert by_name["peak_hbm_bytes"]["verdict"] == "REGRESS"
    assert by_name["step_time_p50"]["verdict"] == "PASS"


# ---------------------------------------------- serialization round-trip

def test_ledger_roundtrips_through_json(get_lowering, tmp_path):
    lg = _ledger(get_lowering("lm_train_dp"))
    path = str(tmp_path / "mem_ledger.json")
    memory.write_ledgers(path, [lg])
    back = memory.load_ledgers(path)[lg.step]
    assert back.peak_bytes == lg.peak_bytes
    assert back.peak_index == lg.peak_index
    assert back.measured_peak_bytes == lg.measured_peak_bytes
    assert back.watermark == lg.watermark
    assert back.mesh_shape == lg.mesh_shape
    fields = back.metrics_fields()
    assert fields["mem_peak_bytes"] == lg.peak_bytes
    # the raw dict keeps the full breakdowns the lossy reload drops
    raw = json.load(open(path))[lg.step]
    assert raw["class_peaks"] == lg.class_peaks()
    assert raw["phase_peaks"] == lg.phase_peaks()


def test_trainer_metrics_fields(get_lowering):
    """The fields the trainers stamp into metrics.jsonl under
    --mem-ledger are the ones obs_report's memory section reads."""
    lg = _ledger(get_lowering("train_image_explicit"))
    fields = lg.metrics_fields()
    for key in ("mem_peak_bytes", "mem_temp_peak_bytes",
                "mem_residual_pct"):
        assert key in fields, fields
    assert fields["mem_peak_bytes"] == lg.peak_bytes
    assert fields["mem_temp_peak_bytes"] == lg.temp_peak_bytes
    assert abs(fields["mem_residual_pct"]) <= 10.0


# ------------------------------------------------ Perfetto counter track

def test_watermark_counter_track(get_lowering):
    """The merged Chrome trace carries the watermark as a "C" (counter)
    track: one event per change point, ts spanning the step window,
    max level equal to the ledger peak."""
    lg = _ledger(get_lowering("train_image_explicit"))
    events = memory.watermark_counter_events(lg, 1000.0, 2000.0, pid=7)
    assert len(events) == len(lg.watermark)
    assert all(e["ph"] == "C" and e["pid"] == 7 for e in events)
    ts = [e["ts"] for e in events]
    assert ts == sorted(ts)
    assert 1000.0 <= ts[0] and ts[-1] <= 2000.0, (ts[0], ts[-1])
    assert max(e["args"]["bytes"] for e in events) == lg.peak_bytes
    # and through the timeline merge path (obs_timeline --mem-ledger)
    tl = timeline.parse_xspace_bytes(timeline.encode_xspace([{
        "name": "/host:CPU",
        "lines": [{"name": "tf_XLATfrtCpuClient/0",
                   "timestamp_ns": 1_000_000,
                   "events": [{"name": "fusion.1", "offset_ps": 0,
                               "duration_ps": 50_000_000}]}],
    }], hostname="host0"), source="rank0")
    merged = timeline.to_chrome_trace([(0, tl)], mem_ledgers=[lg])
    counters = [e for e in merged["traceEvents"] if e.get("ph") == "C"]
    assert len(counters) == len(lg.watermark)
    assert counters[0]["name"].startswith("hbm_watermark")


# --------------------------------------------- zero extra compiles

def test_mem_ledger_rides_lowering_cache(get_lowering):
    """The whole memory sweep is free once shardlint has lowered the
    step: mem_ledger_for must not trigger a single extra compile."""
    get_lowering("train_image_explicit")
    get_lowering("lm_train_dp")
    before = get_lowering.compile_count()
    core.mem_ledger_for("train_image_explicit")
    core.mem_ledger_for("lm_train_dp")
    core.analyze_recipe("train_image_explicit")
    assert get_lowering.compile_count() == before
    # and the conftest cache dir has the artifacts subprocesses read
    assert (get_lowering.cache_dir / "train_image_explicit.hlo").exists()
    meta = json.loads(
        (get_lowering.cache_dir / "train_image_explicit.json").read_text())
    assert meta["measured_peak_bytes"] > 0
    assert "params" in meta["arg_classes"]


# --------------------------------------------- analytic model (±15%)

def test_analytic_image_mem_fence(get_lowering):
    """obs/flops.py first-principles peak model vs the ledger for the
    explicit image step, ±15%."""
    lg = _ledger(get_lowering("train_image_explicit"))
    # TinyMLP: Dense(192->32) + Dense(32->10); batch 16 of 8x8x3 images
    pb = 4 * (192 * 32 + 32 + 32 * 10 + 10)
    act = 4 * 4 * (192 + 32 + 32 + 10)
    data = 16 * 8 * 8 * 3 * 4 / 4 + 16 + 16 + 8
    pred = flops.train_mem_peak(pb, act, data, dp=4, zero=False,
                                explicit_sync=True, metric_bytes=112.0)
    res = flops.mem_residual_pct(pred.peak_bytes, lg.peak_bytes)
    assert res <= 15.0, (pred.peak_bytes, lg.peak_bytes, res)

    lg_z = _ledger(get_lowering("train_image_zero"))
    pred_z = flops.train_mem_peak(pb, act, data, dp=4, zero=True,
                                  explicit_sync=True, metric_bytes=112.0)
    assert pred_z.peak_bytes < pred.peak_bytes
    assert lg_z.peak_bytes < lg.peak_bytes


def test_analytic_lm_mem_fence(get_lowering):
    """lm_train_mem_peak vs the GSPMD LM DP step and its wus twin."""
    lg = _ledger(get_lowering("lm_train_dp"))
    pred = flops.lm_train_mem_peak(64, 32, 1, 4, 8, 16, dp=4)
    res = flops.mem_residual_pct(pred.peak_bytes, lg.peak_bytes)
    assert res <= 15.0, (pred.peak_bytes, lg.peak_bytes, res)

    lg_z = _ledger(get_lowering("train_lm_zero"))
    pred_z = flops.lm_train_mem_peak(64, 32, 1, 4, 8, 16, dp=4, zero=True)
    res_z = flops.mem_residual_pct(pred_z.peak_bytes, lg_z.peak_bytes)
    assert res_z <= 15.0, (pred_z.peak_bytes, lg_z.peak_bytes, res_z)
    # the model agrees with the ledger about the direction of the win
    assert pred_z.peak_bytes < pred.peak_bytes


# ------------------------------------------------ heartbeat memory

def test_heartbeat_memory_roundtrip(tmp_path):
    """beat(mem_bytes=...) -> read_heartbeats -> find_stragglers: the
    flagged rank's reason names its memory."""
    hb = str(tmp_path / "hb")
    now = None
    for pid, step, mem in ((0, 20, 100 << 20), (1, 10, 900 << 20)):
        w = heartbeat.HeartbeatWriter(hb, pid, interval_s=0.0)
        assert w.beat(step, mem_bytes=mem)
    beats = heartbeat.read_heartbeats(hb)
    assert beats[0]["mem"] == 100 << 20
    assert beats[1]["mem"] == 900 << 20
    flagged = heartbeat.find_stragglers(beats, now=now, max_step_lag=3)
    assert 1 in flagged and 0 not in flagged
    assert "mem 900 MiB" in flagged[1], flagged
    # mem is optional: a beat without it neither crashes nor reports it
    w = heartbeat.HeartbeatWriter(hb, 2, interval_s=0.0)
    w.beat(1)
    beats = heartbeat.read_heartbeats(hb)
    assert "mem" not in beats[2]
    flagged = heartbeat.find_stragglers(beats, max_step_lag=3)
    assert "mem" not in flagged[2]


def test_sample_process_memory():
    """On this (Linux, jax-imported) host the sampler returns a positive
    byte count — RSS fallback at worst."""
    m = heartbeat.sample_process_memory()
    assert m is not None and m > 0


# --------------------------------------------- bench staleness aging

def test_bench_staleness_aging(tmp_path):
    lkg = tmp_path / "BENCH_LKG.json"
    ev = tmp_path / "bench_events.jsonl"
    now = 1_700_000_000.0

    # no files at all -> no guess
    assert benchlib.bench_staleness(str(lkg), str(ev), now=now) is None

    lkg.write_text(json.dumps({
        "metric": "img_steps_per_s",
        "captured_at": "2023-11-04T22:13:20+0000"}))  # == now - 10 days
    st = benchlib.bench_staleness(str(lkg), str(ev), now=now)
    assert st["metric"] == "img_steps_per_s"
    assert st["days_stale"] == pytest.approx(10.0, abs=0.2)
    assert st["stale_events"] == 0

    # stale/failed events count but never refresh the last-good mark
    with open(ev, "w") as f:
        f.write(json.dumps({"bench_event": "stale", "t": now - 100}) + "\n")
        f.write(json.dumps({"bench_event": "failed", "t": now - 50}) + "\n")
    st = benchlib.bench_staleness(str(lkg), str(ev), now=now)
    assert st["stale_events"] == 2
    assert st["days_stale"] == pytest.approx(10.0, abs=0.2)

    # an explicit captured event DOES refresh it
    with open(ev, "a") as f:
        f.write(json.dumps({"bench_event": "captured", "t": now - 86400,
                            "captured_at": "yesterday"}) + "\n")
    st = benchlib.bench_staleness(str(lkg), str(ev), now=now)
    assert st["days_stale"] == pytest.approx(1.0, abs=1e-6)
    assert st["last_good"] == "yesterday"


# --------------------------------------------------- CLI selftest (tier-1)

def test_obs_memory_selftest_subprocess():
    """The ledger CLI end to end on the checked-in HLO fixture — fast
    (no jax import on this path)."""
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "obs_memory.py"),
         "--selftest"],
        capture_output=True, text=True, timeout=120, cwd=ROOT)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "selftest OK" in out.stdout


def test_obs_memory_cli_on_cached_hlo(get_lowering, tmp_path):
    """The CLI consumes the conftest cache's HLO artifact of a real
    recipe step in a separate process — pure text re-analysis, no
    recompile, no jax."""
    get_lowering("train_image_explicit")
    hlo = get_lowering.cache_dir / "train_image_explicit.hlo"
    out_json = str(tmp_path / "mem_ledger.json")
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "obs_memory.py"),
         str(hlo), "--json", out_json],
        capture_output=True, text=True, timeout=120, cwd=ROOT)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "ledger train_image_explicit: peak" in out.stdout
    d = json.load(open(out_json))["train_image_explicit"]
    lg = _ledger(get_lowering("train_image_explicit"))
    assert d["peak_bytes"] == lg.peak_bytes
    assert d["watermark"] == [list(p) for p in lg.watermark]
