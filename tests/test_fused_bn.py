"""FusedBatchNormAct ≡ flax BatchNorm (+ReLU): forward, gradients, EMA, eval."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import linen as nn

from pytorch_distributed_tpu.ops.fused_bn import FusedBatchNormAct


def _data(shape=(8, 6, 6, 16), seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(size=shape).astype(np.float32))


class _RefBNRelu(nn.Module):
    relu: bool = False
    use_running_average: bool = False

    @nn.compact
    def __call__(self, x):
        y = nn.BatchNorm(
            use_running_average=self.use_running_average,
            momentum=0.9, epsilon=1e-5,
        )(x)
        return nn.relu(y) if self.relu else y


@pytest.mark.parametrize("relu", [False, True])
def test_forward_matches_flax(relu):
    x = _data()
    ref = _RefBNRelu(relu=relu)
    fused = FusedBatchNormAct(relu=relu)
    vr = ref.init(jax.random.PRNGKey(0), x)
    vf = fused.init(jax.random.PRNGKey(0), x)
    yr, mr = ref.apply(vr, x, mutable=["batch_stats"])
    yf, mf = fused.apply(vf, x, mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(yr), np.asarray(yf), atol=1e-5)
    # EMA running-stats update parity: both mean and var
    ref_stats = mr["batch_stats"]["BatchNorm_0"]
    for k in ("mean", "var"):
        np.testing.assert_allclose(
            np.asarray(ref_stats[k]), np.asarray(mf["batch_stats"][k]),
            atol=1e-5, err_msg=k)


@pytest.mark.parametrize("relu", [False, True])
def test_gradients_match_flax(relu):
    x = _data()
    rng = np.random.default_rng(1)
    gamma = jnp.asarray(rng.normal(size=16).astype(np.float32))
    beta = jnp.asarray(rng.normal(size=16).astype(np.float32))
    ct = jnp.asarray(rng.normal(size=x.shape).astype(np.float32))

    ref = _RefBNRelu(relu=relu)
    fused = FusedBatchNormAct(relu=relu)
    vr = ref.init(jax.random.PRNGKey(0), x)
    vf = fused.init(jax.random.PRNGKey(0), x)
    # inject identical non-trivial scale/bias
    vr = jax.tree_util.tree_map(lambda v: v, vr)
    pr = {"params": {"BatchNorm_0": {"scale": gamma, "bias": beta}},
          "batch_stats": vr["batch_stats"]}
    pf = {"params": {"scale": gamma, "bias": beta},
          "batch_stats": vf["batch_stats"]}

    def loss_ref(params, x):
        y, _ = ref.apply(params, x, mutable=["batch_stats"])
        return (y * ct).sum()

    def loss_fused(params, x):
        y, _ = fused.apply(params, x, mutable=["batch_stats"])
        return (y * ct).sum()

    gr_p, gr_x = jax.grad(loss_ref, argnums=(0, 1))(pr, x)
    gf_p, gf_x = jax.grad(loss_fused, argnums=(0, 1))(pf, x)
    np.testing.assert_allclose(np.asarray(gr_x), np.asarray(gf_x),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(gr_p["params"]["BatchNorm_0"]["scale"]),
        np.asarray(gf_p["params"]["scale"]), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(gr_p["params"]["BatchNorm_0"]["bias"]),
        np.asarray(gf_p["params"]["bias"]), rtol=1e-4, atol=1e-4)


def test_eval_uses_running_stats():
    x = _data()
    fused = FusedBatchNormAct(relu=True)
    v = fused.init(jax.random.PRNGKey(0), x)
    # train a step to move running stats off init
    _, mut = fused.apply(v, x, mutable=["batch_stats"])
    v2 = {"params": v["params"], "batch_stats": mut["batch_stats"]}
    ye = fused.apply(v2, x, use_running_average=True)
    mu = mut["batch_stats"]["mean"]
    var = mut["batch_stats"]["var"]
    expect = jax.nn.relu((x - mu) * jax.lax.rsqrt(var + 1e-5))
    np.testing.assert_allclose(np.asarray(ye), np.asarray(expect), atol=1e-5)


def test_bf16_storage_f32_accumulation():
    x = _data().astype(jnp.bfloat16)
    fused = FusedBatchNormAct(relu=True)
    v = fused.init(jax.random.PRNGKey(0), x)
    y, mut = fused.apply(v, x, mutable=["batch_stats"])
    assert y.dtype == jnp.bfloat16
    assert mut["batch_stats"]["mean"].dtype == jnp.float32
    assert np.isfinite(np.asarray(y, np.float32)).all()


def test_resnet_uses_fused_bn_and_trains():
    """Smoke: resnet18 fwd/bwd with the fused BN under the real train step."""
    from pytorch_distributed_tpu import models
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState
    from pytorch_distributed_tpu.train.steps import make_train_step

    mesh = build_mesh(MeshSpec(("data",), (8,)), jax.devices()[:8])
    model = models.create_model("resnet18", num_classes=4)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)),
                          train=False)
    state = TrainState.create(variables, sgd_init(variables["params"]))
    step = make_train_step(model, mesh)
    rng = np.random.default_rng(0)
    batch = {
        "images": rng.normal(size=(16, 32, 32, 3)).astype(np.float32),
        "labels": rng.integers(0, 4, size=16).astype(np.int32),
        "weights": np.ones(16, np.float32),
    }
    s1, m1 = step(state, batch, jnp.float32(0.1))
    assert np.isfinite(float(m1["loss"]))
