"""Vision Transformer family: registry surface, forward contract, and an
end-to-end Trainer epoch (patch embed / class token / position embeddings
all exercised under the image-harness path)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu import models
from pytorch_distributed_tpu.train.config import Config
from pytorch_distributed_tpu.train.trainer import Trainer


def _tiny(**kw):
    base = dict(num_classes=7, d_model=64, n_layers=2, n_heads=4, mlp_dim=128)
    base.update(kw)
    return models.create_model("vit_b_16", **base)


def test_registry_and_forward():
    assert {"vit_b_16", "vit_b_32", "vit_l_16"} <= set(models.model_names())
    model = _tiny()
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 7)
    assert out.dtype == jnp.float32
    # No BatchNorm: a ViT carries no mutable batch_stats collection.
    assert set(variables) == {"params"}
    # Position embeddings are grid-shaped from the init input (32/16 = 2x2).
    assert variables["params"]["pos_embedding"].shape == (1, 2, 2, 64)


def test_wrong_resolution_fails_loudly():
    model = _tiny()
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)),
                           train=False)
    # Different resolution: grid 4x4 vs stored 2x2 → param shape mismatch.
    with pytest.raises(Exception, match="[Ss]hape"):
        model.apply(variables, jnp.zeros((1, 64, 64, 3)), train=False)
    # Same token count, different aspect (1x4 vs 2x2): must ALSO fail — the
    # grid-shaped pos-embedding param is what catches this silent case.
    with pytest.raises(Exception, match="[Ss]hape"):
        model.apply(variables, jnp.zeros((1, 16, 64, 3)), train=False)


def test_trainer_epoch_with_vit(tmp_path):
    import functools

    # A tiny ViT registered through the public hook: the Trainer resolves it
    # like any zoo arch; position embeddings size themselves from
    # --image-size via the init sample.
    models.register(
        "vit_tiny_test",
        functools.partial(
            models.VisionTransformer, patch_size=16, d_model=32,
            n_layers=2, n_heads=2, mlp_dim=64,
        ),
    )
    cfg = Config(
        arch="vit_tiny_test", batch_size=16, epochs=1, lr=0.01, print_freq=4,
        synthetic=True, synthetic_length=32, image_size=32, num_classes=4,
        seed=0, checkpoint_dir=str(tmp_path), workers=2,
    )
    t = Trainer(cfg)
    p0 = np.asarray(
        jax.tree_util.tree_leaves(t.state.params)[0]).copy()
    best = t.fit()
    p1 = np.asarray(jax.tree_util.tree_leaves(t.state.params)[0])
    assert not np.array_equal(p0, p1), "params must move"
    assert 0.0 <= best <= 100.0
    assert (tmp_path / "checkpoint.msgpack").exists()


def test_remat_parity():
    """remat=True must change NOTHING but memory: same param tree, same
    forward, same grads (guards the static_argnums=(2,) convention in
    models/vit.py against EncoderBlock signature drift)."""
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    kw = dict(patch_size=16, d_model=32, n_layers=2, n_heads=2, mlp_dim=64,
              num_classes=5)
    m0 = models.VisionTransformer(**kw)
    m1 = models.VisionTransformer(**kw, remat=True)
    v0 = m0.init(jax.random.PRNGKey(0), x, train=False)
    v1 = m1.init(jax.random.PRNGKey(0), x, train=False)
    assert (jax.tree_util.tree_structure(v0)
            == jax.tree_util.tree_structure(v1)), "param tree changed"
    y0 = m0.apply(v0, x, train=False)
    y1 = m1.apply(v1, x, train=False)
    np.testing.assert_allclose(np.asarray(y0), np.asarray(y1), rtol=1e-6)
    g0 = jax.grad(lambda v: m0.apply(v, x, train=False).sum())(v0)
    g1 = jax.grad(lambda v: m1.apply(v, x, train=False).sum())(v1)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6), g0, g1)
