"""Recipe CLI flag-collision lint (ISSUE 10 satellite).

The PR-9 bug class: ``add_argument`` with a wrong ``dest`` does not raise
— a later flag can silently *overwrite* another flag's parsed value (the
``--zero`` patch briefly gave lm_pretrain's ``--zero`` the ``precision``
dest, so ``--precision bf16 --zero wus`` dropped the precision on the
floor).  argparse only errors on duplicate option *strings*, never on
shared ``dest``s, so this stays invisible until a run mis-parses.

This lint builds every recipe parser (no devices needed — parsers are
pure argparse) and asserts, recursively through subparsers:

- no duplicate option strings (and the conflict handler is the erroring
  default, so argparse keeps catching those at add time);
- no two actions share a ``dest`` (the silent-overwrite class);
- every flag round-trips: parsing a no-arg line yields exactly one value
  per dest, and the elastic/zero/compress flags parse to their dests.
"""

import argparse
import importlib.util
import os

import pytest

from pytorch_distributed_tpu.recipes import lm_generate, lm_pretrain
from pytorch_distributed_tpu.train import config as config_mod


def _load_script(fname, modname):
    """scripts/ is not a package; load a script by path (heavy imports
    live inside main(), so module load is argparse-only)."""
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", fname)
    spec = importlib.util.spec_from_file_location(modname, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _load_serve_lm():
    return _load_script("serve_lm.py", "serve_lm_flags")


def _load_synclint():
    return _load_script("synclint.py", "synclint_flags")


def _load_serve_fleet():
    return _load_script("serve_fleet.py", "serve_fleet_flags")


PARSERS = {
    # every image recipe (distributed, apex, horovod, slurm, dataparallel,
    # multiprocessing, tpu_native) shares the one canonical parser
    "train.config": lambda: config_mod.build_parser(),
    "recipes.lm_pretrain": lambda: lm_pretrain.build_parser(),
    "recipes.lm_generate": lambda: lm_generate.build_parser(),
    "scripts.serve_lm": lambda: _load_serve_lm().build_parser(),
    "scripts.synclint": lambda: _load_synclint().build_parser(),
    "scripts.serve_fleet": lambda: _load_serve_fleet().build_parser(),
}


def _walk(parser):
    """Yield (parser, action) pairs recursively through subparsers."""
    for act in parser._actions:
        yield parser, act
        if isinstance(act, argparse._SubParsersAction):
            for sub in act.choices.values():
                yield from _walk(sub)


def _lint(parser):
    """Return a list of human-readable collision findings (empty = clean)."""
    findings = []
    by_parser = {}
    for p, act in _walk(parser):
        by_parser.setdefault(id(p), (p, []))[1].append(act)
    for _pid, (p, actions) in by_parser.items():
        seen_opts = {}
        seen_dest = {}
        for act in actions:
            for opt in act.option_strings:
                if opt in seen_opts:
                    findings.append(
                        f"duplicate option string {opt!r} "
                        f"({seen_opts[opt]} vs {act})")
                seen_opts[opt] = act
            if act.dest in (argparse.SUPPRESS, None):
                continue
            if not act.option_strings and act.dest == "command":
                continue
            prev = seen_dest.get(act.dest)
            if prev is not None:
                findings.append(
                    f"dest {act.dest!r} written by two actions: "
                    f"{prev.option_strings or prev.dest} and "
                    f"{act.option_strings or act.dest} — the second "
                    f"silently overwrites the first at parse time")
            seen_dest[act.dest] = act
    return findings


@pytest.mark.parametrize("name", sorted(PARSERS))
def test_no_flag_collisions(name):
    parser = PARSERS[name]()
    findings = _lint(parser)
    assert not findings, f"{name}: " + "; ".join(findings)


@pytest.mark.parametrize("name", sorted(PARSERS))
def test_default_conflict_handler(name):
    """conflict_handler='resolve' would let a duplicate option string
    silently *replace* the earlier flag — keep the erroring default."""
    for p, _ in _walk(PARSERS[name]()):
        assert p.conflict_handler == "error", \
            f"{name}: parser uses conflict_handler={p.conflict_handler!r}"


def test_lint_catches_the_pr9_bug_class():
    """The lint must actually flag a wrong-dest overwrite (regression
    test for the lint itself)."""
    p = argparse.ArgumentParser()
    p.add_argument("--precision", default="fp32")
    p.add_argument("--zero", dest="precision")  # the PR-9 mistake
    findings = _lint(p)
    assert any("precision" in f and "silently overwrites" in f
               for f in findings), findings


def test_elastic_flags_parse_to_their_own_dests():
    """The new ISSUE-10 flags land in their own dests on both surfaces
    and collide with nothing."""
    cfg = config_mod.parse_config(
        ["--elastic", "--min-ranks", "2", "--rescale-lr", "sqrt"])
    assert (cfg.elastic, cfg.min_ranks, cfg.rescale_lr) == (True, 2, "sqrt")
    # defaults stay inert
    cfg = config_mod.parse_config([])
    assert (cfg.elastic, cfg.min_ranks, cfg.rescale_lr) == (False, 1, "none")
    args = lm_pretrain.build_parser().parse_args(
        ["--elastic", "--min-ranks", "2", "--rescale-lr", "linear",
         "--precision", "bf16"])
    assert (args.elastic, args.min_ranks, args.rescale_lr) == \
        (True, 2, "linear")
    assert args.precision == "bf16"  # the PR-9 symptom, pinned


def test_flight_recorder_flags_parse_to_their_own_dests():
    """ISSUE-13 flags: ``--flight-rec``/``--hang-timeout`` land in their
    own dests on both surfaces, default to off/30 s, and collide with
    nothing (the _lint tests above cover the collision half)."""
    cfg = config_mod.parse_config(
        ["--flight-rec", "/tmp/fr", "--hang-timeout", "5"])
    assert (cfg.flight_rec, cfg.hang_timeout) == ("/tmp/fr", 5.0)
    cfg = config_mod.parse_config([])
    assert (cfg.flight_rec, cfg.hang_timeout) == (None, 30.0)
    args = lm_pretrain.build_parser().parse_args(
        ["--flight-rec", "/tmp/fr", "--hang-timeout", "2.5",
         "--precision", "bf16"])
    assert (args.flight_rec, args.hang_timeout) == ("/tmp/fr", 2.5)
    assert args.precision == "bf16"
    args = lm_pretrain.build_parser().parse_args([])
    assert (args.flight_rec, args.hang_timeout) == (None, 30.0)


def test_telemetry_plane_flags_parse_to_their_own_dests():
    """ISSUE-14 flags: ``--metrics-port``/``--alerts`` land in their own
    dests on both surfaces, default to off, and collide with nothing."""
    cfg = config_mod.parse_config(
        ["--metrics-port", "9100", "--alerts", "/tmp/rules.json"])
    assert (cfg.metrics_port, cfg.alerts) == (9100, "/tmp/rules.json")
    cfg = config_mod.parse_config([])
    assert (cfg.metrics_port, cfg.alerts) == (0, None)
    args = lm_pretrain.build_parser().parse_args(
        ["--metrics-port", "9100", "--alerts", "default",
         "--precision", "bf16"])
    assert (args.metrics_port, args.alerts) == (9100, "default")
    assert args.precision == "bf16"
    args = lm_pretrain.build_parser().parse_args([])
    assert (args.metrics_port, args.alerts) == (0, None)


def test_serving_flags_parse_to_their_own_dests():
    """ISSUE-15 flags: serve_lm's model/engine/load/SLO flags land in
    their own dests and collide with nothing (the parametrized _lint
    tests above cover the collision half for this parser too)."""
    ap = _load_serve_lm().build_parser()
    args = ap.parse_args(
        ["--mode", "static", "--kv-blocks", "128", "--gamma", "3",
         "--quant", "int8", "--rate-rps", "10.5", "--slo-ttft-ms", "250",
         "--policy", "priority", "--blocks-per-seq", "6"])
    assert (args.mode, args.kv_blocks, args.gamma) == ("static", 128, 3)
    assert (args.quant, args.rate_rps) == ("int8", 10.5)
    assert (args.slo_ttft_ms, args.policy) == (250.0, "priority")
    assert args.blocks_per_seq == 6
    args = ap.parse_args([])
    assert (args.mode, args.policy) == ("continuous", "fcfs")
    assert (args.slo_ttft_ms, args.slo_kv_pct) == (None, None)
    assert (args.no_watchdog, args.metrics_jsonl) == (False, None)


def test_overlap_flags_parse_to_their_own_dests():
    """ISSUE-16 flags: ``--overlap``/``--bucket-mb`` land in their own
    dests on both surfaces, default to none/4 MiB, and collide with
    nothing (the _lint tests above cover the collision half)."""
    cfg = config_mod.parse_config(
        ["--overlap", "bucketed", "--bucket-mb", "2.5"])
    assert (cfg.overlap, cfg.bucket_mb) == ("bucketed", 2.5)
    cfg = config_mod.parse_config([])
    assert (cfg.overlap, cfg.bucket_mb) == ("none", 4.0)
    args = lm_pretrain.build_parser().parse_args(
        ["--overlap", "bucketed", "--bucket-mb", "0.5",
         "--precision", "bf16"])
    assert (args.overlap, args.bucket_mb) == ("bucketed", 0.5)
    assert args.precision == "bf16"  # the PR-9 symptom, pinned
    args = lm_pretrain.build_parser().parse_args([])
    assert (args.overlap, args.bucket_mb) == ("none", 4.0)


def test_synclint_flags_parse_to_their_own_dests():
    """ISSUE-18 flags: the synclint CLI's layer toggles, baseline pair,
    and jax-free paths land in their own dests, default to everything-on
    with the checked-in baseline, and collide with nothing (the
    parametrized _lint tests above cover the collision half)."""
    ap = _load_synclint().build_parser()
    args = ap.parse_args(
        ["--steps", "lm_train_dp", "--hlo-cache", "/tmp/hlo",
         "--no-ast", "--no-proto", "--json", "/tmp/out.json"])
    assert (args.steps, args.hlo_cache) == ("lm_train_dp", "/tmp/hlo")
    assert (args.no_hlo, args.no_ast, args.no_proto) == (False, True, True)
    assert args.json == "/tmp/out.json"
    args = ap.parse_args([])
    assert (args.no_hlo, args.no_ast, args.no_proto) == (
        False, False, False)
    assert (args.selftest, args.update_baseline, args.no_baseline) == (
        False, False, False)
    assert args.baseline.endswith(os.path.join("analysis", "baseline.json"))
    assert args.hlo_cache is None and args.steps is None


def test_chaoskit_drill_gains_the_desync_kind():
    """ISSUE-18 satellite: ``chaoskit drill desync`` is a real choice and
    the shared ``--seed`` contract flags still parse to their own dests."""
    ck = _load_script("chaoskit.py", "chaoskit_flags")
    import argparse as _ap

    rc_holder = {}

    class _Exit(Exception):
        pass

    def fake_drill(args):
        rc_holder["args"] = args
        raise _Exit()

    orig = ck.cmd_drill
    ck.cmd_drill = fake_drill
    try:
        with pytest.raises(_Exit):
            ck.main(["drill", "desync", "--seed", "3", "--steps", "16"])
    finally:
        ck.cmd_drill = orig
    parsed = rc_holder["args"]
    assert isinstance(parsed, _ap.Namespace)
    assert (parsed.kind, parsed.seed, parsed.steps) == ("desync", 3, 16)
    # the shared contract: the same seed yields the same plan, across
    # every drill kind that derives its step from drill_plan
    assert ck.drill_plan(3, 16) == ck.drill_plan(3, 16)


def test_chaoskit_drill_gains_the_fleet_kinds():
    """ISSUE-19 satellite: ``replica-kill`` and ``router-restart`` are
    real drill choices sharing the seeded ``drill_plan`` contract."""
    ck = _load_script("chaoskit.py", "chaoskit_fleet_flags")

    class _Exit(Exception):
        pass

    got = {}

    def fake_drill(args):
        got["args"] = args
        raise _Exit()

    orig = ck.cmd_drill
    ck.cmd_drill = fake_drill
    try:
        for kind in ("replica-kill", "router-restart"):
            with pytest.raises(_Exit):
                ck.main(["drill", kind, "--seed", "5", "--steps", "12",
                         "--out", "/tmp/x"])
            parsed = got["args"]
            assert (parsed.kind, parsed.seed, parsed.steps,
                    parsed.out) == (kind, 5, 12, "/tmp/x")
    finally:
        ck.cmd_drill = orig
    # the kill point comes from the same seeded plan every drill uses
    assert ck.drill_plan(5, 12) == ck.drill_plan(5, 12)


def test_fleet_flags_parse_to_their_own_dests():
    """ISSUE-19 flags: every serve_fleet subcommand (replica, router,
    arbiter, bench) lands its flags in their own dests with inert
    defaults; the parametrized _lint tests above cover the collision
    half for this parser."""
    ap = _load_serve_fleet().build_parser()
    args = ap.parse_args(
        ["replica", "--replica-id", "3", "--port-file", "/tmp/p",
         "--hb-dir", "/tmp/hb", "--seed", "7", "--sim-itl-ms", "4",
         "--max-batch", "2", "--engine"])
    assert (args.replica_id, args.port_file, args.hb_dir, args.seed,
            args.sim_itl_ms, args.max_batch, args.engine) == (
        3, "/tmp/p", "/tmp/hb", 7, 4.0, 2, True)
    args = ap.parse_args(
        ["router", "--replicas", "0=http://h:1,1=http://h:2",
         "--deadline-s", "9", "--max-retries", "3",
         "--retry-backoff-ms", "10", "--hedge",
         "--hedge-quantile", "0.9", "--hedge-min-ms", "5",
         "--quarantine-backoff-ms", "100",
         "--quarantine-backoff-max-s", "8", "--max-beat-age", "30"])
    assert (args.replicas, args.deadline_s, args.max_retries,
            args.retry_backoff_ms, args.hedge, args.hedge_quantile,
            args.hedge_min_ms, args.quarantine_backoff_ms,
            args.quarantine_backoff_max_s, args.max_beat_age) == (
        "0=http://h:1,1=http://h:2", 9.0, 3, 10.0, True, 0.9, 5.0,
        100.0, 8.0, 30.0)
    args = ap.parse_args(
        ["arbiter", "--hb-dir", "/tmp/hb", "--slo-ttft-ms", "250",
         "--min-replicas", "2", "--max-replicas", "4",
         "--scale-up-pct", "80", "--scale-down-pct", "20", "--once",
         "--spawn-cmd", "x {rid} {port_file}"])
    assert (args.hb_dir, args.slo_ttft_ms, args.min_replicas,
            args.max_replicas, args.scale_up_pct, args.scale_down_pct,
            args.once, args.spawn_cmd) == (
        "/tmp/hb", 250.0, 2, 4, 80.0, 20.0, True, "x {rid} {port_file}")
    args = ap.parse_args(
        ["bench", "--fleet-sizes", "1,2,4", "--requests", "32",
         "--rate-rps", "200", "--min-scaling", "0.75",
         "--out", "/tmp/r.json"])
    assert (args.fleet_sizes, args.requests, args.rate_rps,
            args.min_scaling, args.out) == (
        "1,2,4", 32, 200.0, 0.75, "/tmp/r.json")
    # defaults stay inert
    args = ap.parse_args(["router"])
    assert (args.hedge, args.max_retries, args.seed) == (False, 2, 0)


def test_stepattr_flags_parse_to_their_own_dests():
    """ISSUE-20 flags: ``--step-attr`` lands in its own dest on both
    trainer surfaces, defaults to off, and collides with nothing (the
    parametrized _lint tests above cover the collision half)."""
    cfg = config_mod.parse_config(["--step-attr"])
    assert cfg.step_attr is True
    cfg = config_mod.parse_config([])
    assert cfg.step_attr is False
    args = lm_pretrain.build_parser().parse_args(
        ["--step-attr", "--precision", "bf16"])
    assert args.step_attr is True
    assert args.precision == "bf16"  # the PR-9 symptom, pinned
    args = lm_pretrain.build_parser().parse_args([])
    assert args.step_attr is False


def test_autoplan_gains_attr_from():
    """ISSUE-20 satellite: ``autoplan --attr-from`` is a real flag, is
    exclusive with the other overlap provenances, and is consumed before
    any planning (a missing profile fails loudly, not silently)."""
    apm = _load_script("autoplan.py", "autoplan_attr_flags")
    with pytest.raises(SystemExit):  # one overlap provenance per plan
        apm.main(["lm-tiny", "--attr-from", "/tmp/a.json",
                  "--overlap-from", "/tmp/t.json"])
    with pytest.raises(FileNotFoundError):
        apm.main(["lm-tiny", "--chips", "4",
                  "--attr-from", "/nonexistent/attr.json"])


def test_chaoskit_drill_gains_the_slow_loader_kind():
    """ISSUE-20 satellite: ``chaoskit drill slow-loader`` is a real
    choice sharing the seeded contract flags."""
    ck = _load_script("chaoskit.py", "chaoskit_sl_flags")

    class _Exit(Exception):
        pass

    got = {}

    def fake_drill(args):
        got["args"] = args
        raise _Exit()

    orig = ck.cmd_drill
    ck.cmd_drill = fake_drill
    try:
        with pytest.raises(_Exit):
            ck.main(["drill", "slow-loader", "--seed", "7",
                     "--steps", "10"])
    finally:
        ck.cmd_drill = orig
    parsed = got["args"]
    assert (parsed.kind, parsed.seed, parsed.steps) == \
        ("slow-loader", 7, 10)


def test_trace_and_checkpoint_flags_parse_to_their_own_dests():
    """ISSUE-17 flags: serve_lm's ``--req-trace``/``--trace-sample``
    tracing pair and ``--checkpoint`` land in their own dests, default
    off/0.05/None, and collide with nothing (the parametrized _lint
    tests above cover the collision half for this parser)."""
    ap = _load_serve_lm().build_parser()
    args = ap.parse_args(
        ["--req-trace", "--trace-sample", "0.25",
         "--checkpoint", "/tmp/lm_tiny.msgpack"])
    assert (args.req_trace, args.trace_sample) == (True, 0.25)
    assert args.checkpoint == "/tmp/lm_tiny.msgpack"
    args = ap.parse_args([])
    assert (args.req_trace, args.trace_sample) == (False, 0.05)
    assert args.checkpoint is None
