"""Interleaved (virtual-stage) 1F1B: schedule invariants + numerics parity.

The schedule simulator is a pure host function, so its hazardous part —
the tick mapping — is tested standalone; the pipeline function is then
checked for exact loss/grad parity against a sequential (no-pipeline)
oracle, the same blind-testable pattern pp_1f1b used.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
from pytorch_distributed_tpu.parallel.pp_interleaved import (
    deinterleave_order,
    interleave_order,
    interleaved_pipeline_loss_and_grads,
    simulate_interleaved_schedule,
)


@pytest.mark.parametrize("P_,V,M", [(2, 2, 4), (4, 2, 8), (4, 3, 4),
                                    (8, 2, 8), (4, 1, 8)])
def test_schedule_invariants(P_, V, M):
    s = simulate_interleaved_schedule(P_, V, M)
    C = P_ * V
    # Exactly-once execution of every (chunk, micro) in both directions,
    # correct device placement, stash slots within the reported bound.
    fwd_ticks, bwd_ticks = {}, {}
    for t in range(s.T):
        for d in range(P_):
            if s.f_active[t, d]:
                c = s.f_k[t, d] * P_ + d
                key = (int(c), int(s.f_m[t, d]))
                assert key not in fwd_ticks, key
                fwd_ticks[key] = t
                assert s.f_slot[t, d] < s.S
            if s.b_active[t, d]:
                c = s.b_k[t, d] * P_ + d
                key = (int(c), int(s.b_m[t, d]))
                assert key not in bwd_ticks, key
                bwd_ticks[key] = t
    assert len(fwd_ticks) == C * M
    assert len(bwd_ticks) == C * M
    for (c, m), t in fwd_ticks.items():
        # producer→consumer needs the 1-tick hop (same-device head seed
        # for the last chunk's backward may be same-tick).
        if c > 0:
            assert fwd_ticks[(c - 1, m)] + 1 <= t, (c, m)
        assert bwd_ticks[(c, m)] >= t
        if c < C - 1:
            assert bwd_ticks[(c, m)] >= bwd_ticks[(c + 1, m)] + 1
    # One hop channel each way: at most one F and one B per device-tick
    # is structural (table has one slot); verify the schedule beats plain
    # sequential depth and the stash stays near the analytic bound.
    assert s.T < 2 * C * M  # pipelining actually happens
    assert s.S <= 2 * C + M


@pytest.mark.parametrize("P_,V,M", [(2, 2, 4), (4, 2, 8), (8, 2, 8),
                                    (4, 3, 4), (2, 4, 8), (8, 2, 16),
                                    (4, 4, 8)])
def test_schedule_symbolic_replay(P_, V, M):
    """Replay the tick tables with the COMPILED BODY's exact semantics
    (land-at-start, F phase before B phase, one vin carry per direction,
    head seed written in the F phase) using symbolic value tags — every
    read must see exactly the (chunk, microbatch) value the math needs.
    This is the guard that caught the round-4 seed-overwrite hazard."""
    s = simulate_interleaved_schedule(P_, V, M)
    C = P_ * V
    NONE = ("none",)
    vin_f = [NONE] * P_
    vin_b = [NONE] * P_
    inbox_f = [[NONE] * V for _ in range(P_)]
    inbox_b = [[NONE] * V for _ in range(P_)]
    stash = [[NONE] * s.S for _ in range(P_)]
    for t in range(s.T):
        for d in range(P_):
            if s.rf_active[t, d]:
                inbox_f[d][s.rf_k[t, d]] = vin_f[d]
            if s.rb_active[t, d]:
                inbox_b[d][s.rb_k[t, d]] = vin_b[d]
        sent_f = [NONE] * P_
        sent_b = [NONE] * P_
        for d in range(P_):  # F phase
            if s.f_active[t, d]:
                fk, fm = s.f_k[t, d], s.f_m[t, d]
                c = fk * P_ + d
                x_in = ("feed", fm) if c == 0 else inbox_f[d][fk]
                want = ("feed", fm) if c == 0 else ("out", c - 1, fm)
                assert x_in == want, (t, d, "F", c, fm, x_in)
                stash[d][s.f_slot[t, d]] = ("in", c, fm)
                sent_f[d] = ("out", c, fm)
                if c == C - 1:
                    inbox_b[d][V - 1] = ("dy", C - 1, fm)
            else:
                sent_f[d] = ("garbage", t, d)
        for d in range(P_):  # B phase
            if s.b_active[t, d]:
                bk, bm = s.b_k[t, d], s.b_m[t, d]
                c = bk * P_ + d
                assert stash[d][s.b_slot[t, d]] == ("in", c, bm), (t, d, c)
                assert inbox_b[d][bk] == ("dy", c, bm), (t, d, c, bm,
                                                        inbox_b[d][bk])
                sent_b[d] = (("dy", c - 1, bm) if c > 0
                             else ("dmicro", bm))
            else:
                sent_b[d] = ("garbageB", t, d)
        vin_f = [sent_f[(d - 1) % P_] for d in range(P_)]
        vin_b = [sent_b[(d + 1) % P_] for d in range(P_)]


def test_schedule_stash_reported():
    s = simulate_interleaved_schedule(4, 2, 8)
    # The interleave trades bubble for stash: bound must be > plain-1F1B's
    # 2(P-1)+1 = 7 but far below GPipe's M*V = 16 per-chunk stashes.
    assert 7 <= s.S <= 16, s.S


def _toy(P_, V, d_model=8, mb=2, M=4, seed=0):
    """Toy chunk stack: C linear+tanh chunks, CE-ish quadratic head."""
    C = P_ * V
    rng = np.random.default_rng(seed)
    chunk_params = {
        "w": jnp.asarray(rng.normal(size=(C, d_model, d_model), scale=0.5)
                         .astype(np.float32)),
        "b": jnp.asarray(rng.normal(size=(C, d_model)).astype(np.float32)
                         * 0.1),
    }
    head = {"h": jnp.asarray(rng.normal(size=(d_model,)).astype(np.float32))}
    B = M * mb
    x = jnp.asarray(rng.normal(size=(B, 4, d_model)).astype(np.float32))
    tokens = jnp.asarray(rng.integers(0, 5, size=(B, 4)).astype(np.int32))

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"] + p["b"])

    def head_fn(hp, y, tok):
        # differentiable scalar + a "correct count" aux
        z = jnp.sum((y * hp["h"]) ** 2) / y.shape[0]
        correct = jnp.sum(tok).astype(jnp.float32)
        return z, correct

    return chunk_params, head, x, tokens, stage_fn, head_fn


def _sequential_oracle(chunk_params, head, x, tokens, stage_fn, head_fn, M):
    """Mean-over-microbatches loss + autodiff grads, no pipeline."""
    loss, grads = jax.value_and_grad(
        lambda cp, hp: loss_fn_with_x(cp, hp, x, tokens, stage_fn,
                                      head_fn, M),
        argnums=(0, 1))(chunk_params, head)
    dx = jax.grad(
        lambda xx: loss_fn_with_x(chunk_params, head, xx, tokens,
                                  stage_fn, head_fn, M))(x)
    return loss, grads[0], grads[1], dx


def loss_fn_with_x(cp, hp, x, tokens, stage_fn, head_fn, M):
    C = cp["w"].shape[0]
    mb = x.shape[0] // M
    total = 0.0
    for m in range(M):
        y = x[m * mb:(m + 1) * mb]
        for c in range(C):
            y = stage_fn({"w": cp["w"][c], "b": cp["b"][c]}, y)
        z, _ = head_fn(hp, y, tokens[m * mb:(m + 1) * mb])
        total = total + z
    return total / M


@pytest.mark.parametrize("P_,V,M", [(4, 2, 8), (2, 2, 4), (4, 1, 4)])
def test_interleaved_matches_sequential(P_, V, M):
    mesh = build_mesh(MeshSpec(("pipe",), (P_,)), jax.devices()[:P_])
    chunk_params, head, x, tokens, stage_fn, head_fn = _toy(P_, V, M=M)
    want_loss, want_gc, want_gh, want_dx = _sequential_oracle(
        chunk_params, head, x, tokens, stage_fn, head_fn, M)

    perm = interleave_order(P_, V)
    dm_params = jax.tree_util.tree_map(lambda a: a[perm], chunk_params)
    loss, correct, count, g_dm, g_head, dx = (
        interleaved_pipeline_loss_and_grads(
            stage_fn, head_fn, dm_params, head, x, tokens, M, V, mesh))
    # device-major → natural: dm[i] = nat[perm[i]] ⇒ nat = dm[inv].
    inv = deinterleave_order(P_, V)
    g_nat = jax.tree_util.tree_map(lambda a: a[inv], g_dm)
    np.testing.assert_allclose(float(loss), float(want_loss),
                               rtol=1e-5, atol=1e-6)
    for k in ("w", "b"):
        np.testing.assert_allclose(np.asarray(g_nat[k]),
                                   np.asarray(want_gc[k]),
                                   rtol=2e-4, atol=1e-5, err_msg=k)
    np.testing.assert_allclose(np.asarray(g_head["h"]),
                               np.asarray(want_gh["h"]),
                               rtol=2e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(want_dx),
                               rtol=2e-4, atol=1e-5)
    assert float(count) == x.shape[0] * (tokens.shape[1] - 1)


def test_interleaved_model_matches_gpipe_two_steps():
    """PipelinedTransformerLM(schedule='interleaved', V=2) vs the gpipe
    model over the SAME network: chunks are re-stitched into gpipe's
    2-blocks-per-stage layout, then two full train steps must produce the
    same loss/acc trajectory (step 2's loss goes through step 1's
    manual-gradient update — backward correctness end to end)."""
    from jax.sharding import NamedSharding, PartitionSpec as PS

    from pytorch_distributed_tpu.models.pipeline_lm import (
        PipelinedTransformerLM,
        pp_specs,
    )
    from pytorch_distributed_tpu.parallel.tp import shard_state
    from pytorch_distributed_tpu.train.lm import make_lm_train_step
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState

    VOCAB, D, HEADS, LAYERS, STAGES, V, SEQ, BATCH = 64, 32, 2, 4, 2, 2, 16, 8
    M = 2
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, VOCAB, size=(BATCH, SEQ)).astype(np.int32)

    def run(schedule, params_override=None, n_virtual=1):
        mesh = build_mesh(MeshSpec(("data", "pipe"), (2, STAGES)),
                          jax.devices()[:2 * STAGES])
        model = PipelinedTransformerLM(
            vocab_size=VOCAB, d_model=D, n_heads=HEADS, n_layers=LAYERS,
            n_stages=STAGES, n_microbatches=M, mesh=mesh,
            schedule=schedule, n_virtual=n_virtual,
        )
        with mesh:
            params = model.init(jax.random.PRNGKey(0), tokens)["params"]
            if params_override is not None:
                params = params_override(params)
            # host snapshot BEFORE stepping: the train step donates the
            # state, deleting the original device buffers.
            snap = jax.device_get(params)
            spec = pp_specs(params)
            state = shard_state(
                TrainState.create({"params": params}, sgd_init(params)),
                spec, mesh,
            )
            step = make_lm_train_step(model, mesh, spec, weight_decay=0.0)
            toks = jax.device_put(
                tokens, NamedSharding(mesh, PS("data", None)))
            out = []
            for _ in range(2):
                state, metrics = step(state, toks, jnp.float32(0.05))
                out.append({k: float(v) for k, v in metrics.items()})
            return snap, out

    # Interleaved model: C = 4 chunks of 1 block, device-major layout.
    il_params, il_metrics = run("interleaved", n_virtual=V)
    inv = deinterleave_order(STAGES, V)
    nat = jax.tree_util.tree_map(lambda a: a[inv], il_params["stages"])

    # Stitch natural chunks (1 block each) into gpipe's layout
    # (STAGES stages × 2 blocks): stage s block b = chunk s*V + b.
    def to_gpipe(gp_params):
        st = {}
        for b in range(V):
            src = nat["block_0"]
            st[f"block_{b}"] = jax.tree_util.tree_map(
                lambda a: a[np.asarray([s * V + b for s in range(STAGES)])],
                src)
        return {"embed": il_params["embed"], "ln_f": il_params["ln_f"],
                "stages": st}

    _, gp_metrics = run("gpipe", params_override=to_gpipe)
    for a, b in zip(il_metrics, gp_metrics):
        assert a["loss"] == pytest.approx(b["loss"], rel=2e-4), (a, b)
        assert a["acc"] == pytest.approx(b["acc"], abs=1e-3)


def test_lm_pretrain_interleaved_fsdp_runs_and_learns(capsys, tmp_path):
    """The recipe surface: --schedule interleaved --pp-virtual 2 composed
    with --fsdp (stage params gather at the shard_map boundary exactly as
    the 1f1b schedule's do) — runs end-to-end and learns."""
    from pytorch_distributed_tpu.recipes import lm_pretrain

    final = lm_pretrain.main([
        "--vocab", "32", "--d-model", "32", "--n-heads", "2",
        "--n-layers", "8", "--seq-len", "16", "-b", "8",
        "--steps", "8", "--lr", "0.05", "-p", "2",
        "--dataset-length", "8", "--precision", "fp32",
        "--pp", "4", "--schedule", "interleaved", "--pp-virtual", "2",
        "--fsdp", "--no-eval", "--checkpoint-dir", str(tmp_path),
    ])
    out = capsys.readouterr().out
    first = float(out.split("Loss ")[1].split(" ")[0])
    assert final < first


def test_interleaved_rejects_bad_config():
    from pytorch_distributed_tpu.recipes import lm_pretrain

    with pytest.raises(SystemExit, match="divisible"):
        lm_pretrain.main([
            "--pp", "4", "--schedule", "interleaved", "--pp-virtual", "3",
            "--n-layers", "8", "--steps", "1",
        ])


def test_interleaved_composes_with_data_axis():
    """(data 2, pipe 4) mesh: the microbatch batch dim sharded over data."""
    P_, V, M = 4, 2, 4
    mesh = build_mesh(MeshSpec(("data", "pipe"), (2, P_)),
                      jax.devices()[:2 * P_])
    chunk_params, head, x, tokens, stage_fn, head_fn = _toy(
        P_, V, M=M, mb=2)
    want_loss, want_gc, _, _ = _sequential_oracle(
        chunk_params, head, x, tokens, stage_fn, head_fn, M)
    perm = interleave_order(P_, V)
    dm_params = jax.tree_util.tree_map(lambda a: a[perm], chunk_params)
    loss, _, _, g_dm, _, _ = interleaved_pipeline_loss_and_grads(
        stage_fn, head_fn, dm_params, head, x, tokens, M, V, mesh)
    inv = deinterleave_order(P_, V)
    g_nat = jax.tree_util.tree_map(lambda a: a[inv], g_dm)
    np.testing.assert_allclose(float(loss), float(want_loss),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(g_nat["w"]),
                               np.asarray(want_gc["w"]),
                               rtol=2e-4, atol=1e-5)
