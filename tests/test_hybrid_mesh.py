"""Multi-slice (ICI × DCN) mesh construction — parallel/mesh.py."""

import jax
import numpy as np
import pytest

from pytorch_distributed_tpu.parallel import (
    MeshSpec,
    build_hybrid_mesh,
    build_mesh,
)


def test_single_slice_falls_back_to_flat_mesh():
    """CPU-sim devices carry no slice topology — build_hybrid_mesh must
    degrade to plain build_mesh with identical device placement."""
    spec = MeshSpec(("data", "model"), (4, 2))
    hybrid = build_hybrid_mesh(spec, devices=jax.devices()[:8])
    flat = build_mesh(spec, jax.devices()[:8])
    assert hybrid.axis_names == flat.axis_names
    assert hybrid.shape == flat.shape
    assert (np.asarray(hybrid.devices) == np.asarray(flat.devices)).all()


def test_rejects_unknown_dcn_axis():
    with pytest.raises(ValueError, match="dcn_axis"):
        build_hybrid_mesh(MeshSpec(("data",), (-1,)), dcn_axis="pipe",
                          devices=jax.devices()[:8])


class _FakeSliceDevice:
    """Stub with the slice topology attribute the hybrid path dispatches on."""

    def __init__(self, i, n_per_slice):
        self.id = i
        self.slice_index = i // n_per_slice
        self.process_index = self.slice_index


def test_multi_slice_splits_dcn_axis(monkeypatch):
    """With 2 fake slices × 4 devices, the data axis (8) must decompose into
    ici=4 per slice × dcn=2 across slices, delegated to
    mesh_utils.create_hybrid_device_mesh."""
    from jax.experimental import mesh_utils

    fakes = [_FakeSliceDevice(i, 4) for i in range(8)]
    captured = {}

    def fake_hybrid(ici_shape, dcn_shape, devices, **kw):
        captured["ici"] = tuple(ici_shape)
        captured["dcn"] = tuple(dcn_shape)
        captured["n"] = len(devices)
        return np.asarray(jax.devices()[:8]).reshape(
            tuple(i * d for i, d in zip(ici_shape, dcn_shape)))

    monkeypatch.setattr(mesh_utils, "create_hybrid_device_mesh", fake_hybrid)
    mesh = build_hybrid_mesh(MeshSpec(("data",), (-1,)), devices=fakes)
    assert captured == {"ici": (4,), "dcn": (2,), "n": 8}
    assert mesh.shape == {"data": 8}


def test_multi_slice_dcn_axis_must_divide():
    fakes = [_FakeSliceDevice(i, 2) for i in range(6)]  # 3 slices × 2
    with pytest.raises(ValueError, match="not divisible"):
        # data axis carries 2 of 6 devices → 2 % 3 slices != 0
        build_hybrid_mesh(MeshSpec(("data", "model"), (2, 3)), devices=fakes)


def test_multi_slice_inner_axis_stays_in_slice(monkeypatch):
    """Only the dcn axis is split across slices; model stays ICI-local."""
    from jax.experimental import mesh_utils

    fakes = [_FakeSliceDevice(i, 4) for i in range(8)]
    captured = {}

    def fake_hybrid(ici_shape, dcn_shape, devices, **kw):
        captured["ici"] = tuple(ici_shape)
        captured["dcn"] = tuple(dcn_shape)
        return np.asarray(jax.devices()[:8]).reshape(
            tuple(i * d for i, d in zip(ici_shape, dcn_shape)))

    monkeypatch.setattr(mesh_utils, "create_hybrid_device_mesh", fake_hybrid)
    mesh = build_hybrid_mesh(MeshSpec(("data", "model"), (4, 2)),
                             devices=fakes)
    assert captured["ici"] == (2, 2)  # data 4 = 2/slice × 2 slices
    assert captured["dcn"] == (2, 1)  # model never crosses DCN
    assert mesh.shape == {"data": 4, "model": 2}
