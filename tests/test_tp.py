"""Tensor parallelism: sharded-param LM step ≡ replicated-param step, and
the lm_pretrain recipe learns under dp, tp, and sp."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.models.transformer import TransformerLM
from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
from pytorch_distributed_tpu.parallel.tp import replicated_like, tp_specs
from pytorch_distributed_tpu.train.lm import (
    LMTrainer,
    SyntheticTokenDataset,
    make_lm_train_step,
)
from pytorch_distributed_tpu.train.optim import sgd_init
from pytorch_distributed_tpu.train.state import TrainState
from jax.sharding import PartitionSpec as P


def _model(vocab=64, d_model=64, heads=4, layers=2):
    return TransformerLM(vocab_size=vocab, d_model=d_model, n_heads=heads,
                         n_layers=layers)


def _tokens(B=8, L=32, vocab=64, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, vocab, size=(B, L)).astype(np.int32)


def test_tp_specs_cover_all_params():
    model = _model()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    specs = tp_specs(params)
    flat = jax.tree_util.tree_leaves_with_path(specs)
    sharded = [p for p, s in flat if s != P()]
    # embedding + per-layer qkv/proj/fc1/fc2 kernels must be sharded
    assert len(sharded) == 1 + 4 * 2
    for path, spec in flat:
        assert isinstance(spec, P)


def test_tp_step_matches_replicated_step():
    mesh_tp = build_mesh(MeshSpec(("data", "model"), (2, 4)), jax.devices()[:8])
    mesh_dp = build_mesh(MeshSpec(("data",), (8,)), jax.devices()[:8])
    model = _model()
    tokens = _tokens()
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(tokens[:1]))
    params = variables["params"]

    def run(mesh, specs):
        from pytorch_distributed_tpu.parallel.tp import shard_state

        fresh = jax.tree_util.tree_map(jnp.array, params)
        state = shard_state(
            TrainState.create({"params": fresh}, sgd_init(fresh)), specs, mesh
        )
        step = make_lm_train_step(model, mesh, specs)
        s1, m = step(state, jnp.asarray(tokens), jnp.float32(0.05))
        return s1, m

    s_tp, m_tp = run(mesh_tp, tp_specs(params))
    s_dp, m_dp = run(mesh_dp, replicated_like(params))
    np.testing.assert_allclose(float(m_tp["loss"]), float(m_dp["loss"]),
                               rtol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(s_tp.params),
                    jax.tree_util.tree_leaves(s_dp.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_tp_params_actually_sharded():
    mesh = build_mesh(MeshSpec(("data", "model"), (2, 4)), jax.devices()[:8])
    model = _model()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    from pytorch_distributed_tpu.parallel.tp import shard_pytree

    sharded = shard_pytree(params, tp_specs(params), mesh)
    qkv = sharded["block_0"]["attn"]["qkv"]["kernel"]
    # Column-parallel: each device holds 1/4 of the output features.
    local = qkv.addressable_shards[0].data
    assert local.shape[1] == qkv.shape[1] // 4
    assert local.shape[0] == qkv.shape[0]


@pytest.mark.parametrize("kind", ["dp", "tp", "sp"])
def test_lm_pretrain_recipe_learns(kind, tmp_path, capsys):
    from pytorch_distributed_tpu.recipes import lm_pretrain

    # dataset-length == batch: the same batch every step (memorizable), so
    # a dozen SGD steps must visibly reduce loss.
    args = ["--vocab", "32", "--d-model", "32", "--n-heads", "2",
            "--n-layers", "1", "--seq-len", "32", "-b", "8",
            "--steps", "15", "--lr", "0.05", "-p", "4",
            "--dataset-length", "8",
            "--precision", "fp32", "--checkpoint-dir", str(tmp_path)]
    if kind == "tp":
        args += ["--tp", "4"]
    elif kind == "sp":
        args += ["--sp", "4"]
    final = lm_pretrain.main(args)
    out = capsys.readouterr().out
    assert "Step: " in out and "Final loss" in out
    first = float(out.split("Loss ")[1].split(" ")[0])
    assert final < first  # the affine token process is learnable
    assert (tmp_path / "checkpoint.msgpack").exists()


def test_lm_pretrain_rejects_ep_combined():
    from pytorch_distributed_tpu.recipes import lm_pretrain

    with pytest.raises(SystemExit):
        lm_pretrain.main(["--ep", "2", "--tp", "2"])


def test_lm_pretrain_rejects_indivisible_heads():
    from pytorch_distributed_tpu.recipes import lm_pretrain

    with pytest.raises(SystemExit):
        lm_pretrain.main(["--tp", "4", "--n-heads", "2", "--sp", "2"])
