"""Resilient serving fleet (ISSUE 19): router, replicas, drills.

Three planes:

- subprocess selftests: ``serve_fleet.py --selftest`` is the heavy
  in-process battery (registry probing, dispatch, retries, hedging,
  drain, arbiter scale/evict, gauge round-trip) and must run with **no
  jax in the process** — the fleet is a login-node/sidecar surface;
- the chaoskit fleet drills: ``drill replica-kill`` (SIGKILL a replica
  mid-decode; every request completes exactly once, bit-exact vs an
  unkilled baseline) and ``drill router-restart`` (SIGKILL the router;
  client replays land exactly once through the replicas' rid caches);
- jax-free unit checks on the path-loaded router module: the
  exactly-once ledger, the pure scale decision, deterministic sim
  tokens — cheap guards that don't need sockets.
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPTS = os.path.join(REPO, "scripts")


def _load_serving(name, alias):
    path = os.path.join(REPO, "pytorch_distributed_tpu", "serving",
                        f"{name}.py")
    spec = importlib.util.spec_from_file_location(alias, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[alias] = mod
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------ selftests

def test_serve_fleet_selftest_runs_clean_and_jax_free():
    out = subprocess.run(
        [sys.executable, os.path.join(SCRIPTS, "serve_fleet.py"),
         "--selftest"],
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "serve_fleet selftest: OK" in out.stdout


def test_router_module_imports_without_jax():
    """The import-time hygiene fence: loading serving/router.py and
    serving/replica.py by path must never drag jax into the process
    (the same ``_sibling_module`` discipline as obs/alerts.py)."""
    code = (
        "import importlib.util, sys\n"
        "for name in ('router', 'replica'):\n"
        "    p = ('pytorch_distributed_tpu/serving/%s.py' % name)\n"
        "    spec = importlib.util.spec_from_file_location(\n"
        "        '_t_' + name, p)\n"
        "    m = importlib.util.module_from_spec(spec)\n"
        "    sys.modules['_t_' + name] = m\n"
        "    spec.loader.exec_module(m)\n"
        "assert 'jax' not in sys.modules, 'router import pulled in jax'\n"
        "print('jax-free')\n")
    out = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "jax-free" in out.stdout


# ---------------------------------------------------------- chaos drills

def test_drill_replica_kill_zero_loss(tmp_path):
    """The ISSUE-19 fence: SIGKILL a replica mid-decode — zero lost,
    zero double-completed, tokens bit-exact vs the unkilled baseline,
    replica_down ft_event + alert booked, obs_report folds the fleet
    section."""
    out = subprocess.run(
        [sys.executable, os.path.join(SCRIPTS, "chaoskit.py"), "drill",
         "replica-kill", "--steps", "12", "--seed", "3",
         "--out", str(tmp_path)],
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "drill replica-kill: OK" in out.stdout
    assert "zero lost, zero double-completed" in out.stdout


@pytest.mark.slow
def test_drill_router_restart_zero_loss(tmp_path):
    out = subprocess.run(
        [sys.executable, os.path.join(SCRIPTS, "chaoskit.py"), "drill",
         "router-restart", "--steps", "12", "--seed", "3",
         "--out", str(tmp_path)],
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "drill router-restart: OK" in out.stdout


# ------------------------------------------------- jax-free unit checks

def test_completion_ledger_is_exactly_once():
    router = _load_serving("router", "_t_fleet_router")
    led = router.CompletionLedger(max_entries=4)
    assert led.book(1, {"tokens": [1, 2]})
    assert not led.book(1, {"tokens": [9, 9]}), "second booking must lose"
    assert led.get(1) == {"tokens": [1, 2]}, "first completion wins"
    for rid in range(2, 7):
        led.book(rid, {"tokens": [rid]})
    assert led.get(1) is None, "LRU cap must evict the oldest"
    assert led.get(6) == {"tokens": [6]}


def test_decide_scale_directions():
    router = _load_serving("router", "_t_fleet_router")

    def row(rid, ttft, queue):
        return {"rid": rid, "state": router.UP, "queue_depth": queue,
                "kv_occupancy_pct": 10.0, "ttft_p99_ms": ttft,
                "inflight": 0}

    up, _, why = router.decide_scale(
        [row(0, 480.0, 0.0)], slo_ttft_ms=500.0)
    assert up == "up" and "ttft_p99" in why
    down, victim, _ = router.decide_scale(
        [row(0, 10.0, 0.0), row(1, 10.0, 0.0)], slo_ttft_ms=500.0)
    assert down == "down" and victim in (0, 1)
    hold, _, _ = router.decide_scale(
        [row(0, 250.0, 1.0)], slo_ttft_ms=500.0)
    assert hold is None
    floor, _, _ = router.decide_scale(
        [row(0, 10.0, 0.0)], slo_ttft_ms=500.0, min_replicas=1)
    assert floor is None, "never scale below min_replicas"


def test_sim_tokens_deterministic_across_replicas():
    replica = _load_serving("replica", "_t_fleet_replica")
    a = replica.sim_tokens([1, 2, 3], 8, 64, seed=7)
    b = replica.sim_tokens([1, 2, 3], 8, 64, seed=7)
    assert a == b and len(a) == 8
    assert replica.sim_tokens([1, 2, 3], 8, 64, seed=8) != a
    assert all(0 <= t < 64 for t in a)


def test_fleet_reconciliation_contract():
    """The obs_trace acceptance identity, checked at the library level:
    router_ttft == router_wait + redispatch + hedge_wait + engine_ttft,
    and the echoed engine TTFT matches the engine's own record."""
    from pytorch_distributed_tpu.obs import reqtrace

    fleet = [{"ft_event": "fleettrace", "rid": 0, "replica": 1,
              "attempts": 2, "hedged": 0, "router_wait_ms": 1.5,
              "redispatch_ms": 20.0, "hedge_wait_ms": 0.0,
              "engine_ttft_ms": 40.0, "router_ttft_ms": 61.5}]
    engine = [{"ft_event": "reqtrace", "rid": 0, "ttft_ms": 40.0}]
    rec = reqtrace.fleet_reconciliation(fleet, engine)
    assert rec["requests"] == 1 and rec["retried"] == 1
    assert rec["decomp_err_ms_max"] < 1e-9
    assert rec["engine_matched"] == 1
    assert rec["engine_echo_err_ms_max"] < 1e-9
    assert reqtrace.fleet_reconciliation([], engine) is None


def test_bench_results_pin_scaling_fence():
    """RESULTS_fleet.json (the checked-in artifact) pins the zero-loss
    and ≥0.8x-linear scaling fences this PR claims."""
    path = os.path.join(REPO, "RESULTS_fleet.json")
    assert os.path.exists(path), "RESULTS_fleet.json missing"
    with open(path) as f:
        res = json.load(f)
    bench = res["bench"]
    assert bench["all_completed"] is True
    assert bench["scaling_vs_linear"] >= 0.8
    for drill in ("replica_kill", "router_restart"):
        assert res[drill]["lost"] == 0
        assert res[drill]["double_completed"] == 0
