"""Recipe smoke matrix — the de-facto test the reference ran by hand
(start.sh launches, SURVEY.md §4 item 1), executed on the simulated mesh.

Every smoke recipe trains the identical resnet18/32px/batch-16 config,
so their train/eval steps are the same program compiled N times.  The
module-scoped ``shared_step_builders`` fixture memoizes the trainer's
step builders by build fingerprint (the compile-budget discipline of
analysis/lowering.py, applied to the smoke matrix's private jit
compiles), and the tail tests assert the sharing actually happened and
that the session's AOT compile budget didn't grow."""

import jax
import numpy as np
import pytest

from pytorch_distributed_tpu.recipes import (
    apex_distributed,
    dataparallel,
    distributed,
    horovod_distributed,
    multiprocessing_distributed,
    tpu_native,
)
from pytorch_distributed_tpu.recipes import distributed_slurm_main

SMOKE_ARGS = [
    "--synthetic",
    "--synthetic-length", "32",
    "-a", "resnet18",
    "--image-size", "32",
    "--num-classes", "4",
    "-b", "16",
    "--epochs", "1",
    "-p", "1",
    "--seed", "0",
]


def _args(tmp_path, extra=()):
    return SMOKE_ARGS + ["--checkpoint-dir", str(tmp_path)] + list(extra)


def _fingerprint(v):
    """Hashable build key for one step-builder argument: arrays reduce to
    shape/dtype (the lowering only depends on avals, and repr'ing ResNet
    params would materialize them), pytrees recurse, the rest reprs."""
    if hasattr(v, "shape") and hasattr(v, "dtype"):
        return ("aval", tuple(v.shape), str(v.dtype))
    leaves, treedef = jax.tree_util.tree_flatten(v)
    if len(leaves) != 1 or leaves[0] is not v:
        return (str(treedef),) + tuple(_fingerprint(l) for l in leaves)
    return repr(v)


@pytest.fixture(scope="module", autouse=True)
def shared_step_builders():
    """Memoize make_train_step/make_eval_step at the trainer's import
    site: identical build fingerprints reuse one jitted step (and so one
    XLA compile) across the whole smoke matrix."""
    from pytorch_distributed_tpu.train import trainer as trainer_mod

    real = {"train": trainer_mod.make_train_step,
            "eval": trainer_mod.make_eval_step}
    cache = {}
    stats = {"train_calls": 0, "train_builds": 0,
             "eval_calls": 0, "eval_builds": 0}

    def _mesh_key(mesh):
        return (tuple(dict(mesh.shape).items()),
                tuple(d.id for d in mesh.devices.flat))

    def _memo(which):
        def build(model, mesh, **kw):
            stats[f"{which}_calls"] += 1
            key = (which, str(model), _mesh_key(mesh),
                   tuple(sorted((k, _fingerprint(v))
                                for k, v in kw.items())))
            if key not in cache:
                stats[f"{which}_builds"] += 1
                cache[key] = real[which](model, mesh, **kw)
            return cache[key]
        return build

    mp = pytest.MonkeyPatch()
    mp.setattr(trainer_mod, "make_train_step", _memo("train"))
    mp.setattr(trainer_mod, "make_eval_step", _memo("eval"))
    yield stats
    mp.undo()


@pytest.mark.parametrize(
    "recipe",
    [
        dataparallel,
        distributed,
        multiprocessing_distributed,
        apex_distributed,
        horovod_distributed,
        distributed_slurm_main,
        tpu_native,
    ],
    ids=lambda m: m.__name__.rsplit(".", 1)[-1],
)
def test_recipe_trains_one_epoch(recipe, tmp_path, capsys, monkeypatch):
    monkeypatch.chdir(tmp_path)  # recipes with epoch CSVs write into cwd
    best = recipe.main(_args(tmp_path))
    out = capsys.readouterr().out
    assert "Epoch: [0]" in out
    assert "* Acc@1" in out
    assert 0.0 <= best <= 100.0
    assert (tmp_path / "checkpoint.msgpack").exists()


def test_epoch_csv_written_by_dataparallel(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    dataparallel.main(_args(tmp_path))
    csv_path = tmp_path / "dataparallel.csv"
    assert csv_path.exists()
    lines = csv_path.read_text().strip().splitlines()
    assert lines[0] == "timestamp,epoch_seconds"  # self-describing header
    row = lines[1].split(",")
    assert len(row) == 2 and float(row[1]) > 0


def test_evaluate_flag(tmp_path, capsys):
    best = tpu_native.main(_args(tmp_path, ["-e"]))
    out = capsys.readouterr().out
    assert "* Acc@1" in out and "Epoch: [0]" not in out


def test_lm_generate_recipe(tmp_path, capsys):
    """Serving CLI: train a tiny byte-LM, checkpoint it, sample from the
    checkpoint via the lm_generate recipe (tokens + decoded text)."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.models.transformer import TransformerLM
    from pytorch_distributed_tpu.recipes import lm_generate
    from pytorch_distributed_tpu.train.checkpoint import save_checkpoint
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState

    cfg = dict(vocab_size=256, d_model=32, n_heads=4, n_layers=2)
    model = TransformerLM(**cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    state = TrainState.create({"params": params}, sgd_init(params))
    path = save_checkpoint(str(tmp_path), state, epoch=3,
                           arch="transformer_lm", best_acc1=0.0,
                           is_best=False)

    rc = lm_generate.main([
        "--resume", path, "--vocab", "256", "--d-model", "32",
        "--n-heads", "4", "--n-layers", "2", "--prompt", "ab",
        "-n", "4", "--temperature", "1.0", "--top-k", "5", "--top-p",
        "0.9", "--seed", "1",
    ])
    outp = capsys.readouterr().out
    assert rc == 0
    assert "epoch 3" in outp and "tokens:" in outp and "text:" in outp

    # --random-init smoke with explicit token ids, no checkpoint
    rc2 = lm_generate.main([
        "--random-init", "--vocab", "64", "--d-model", "32", "--n-heads",
        "4", "--n-layers", "2", "--prompt-tokens", "1,2,3", "-n", "3",
    ])
    assert rc2 == 0


def test_lm_generate_speculative(capsys):
    """--spec-draft: the greedy speculative stream through the CLI equals
    the CLI's own target-only greedy stream (output-distribution identity
    at temperature 0), and the stats line is printed."""
    from pytorch_distributed_tpu.recipes import lm_generate

    base = ["--random-init", "--vocab", "64", "--d-model", "32",
            "--n-heads", "4", "--n-layers", "2", "--prompt-tokens",
            "1,2,3", "-n", "8", "--seed", "3"]
    assert lm_generate.main(base) == 0
    want = capsys.readouterr().out
    assert lm_generate.main(
        base + ["--spec-draft", "random", "--spec-gamma", "2"]) == 0
    got = capsys.readouterr().out
    assert "speculative:" in got and "tok/pass" in got
    tok = [ln for ln in want.splitlines() if ln.startswith("tokens:")]
    assert tok and tok[0] in got


def test_smoke_matrix_shared_step_compiles(shared_step_builders):
    """The migration fence: the smoke matrix's identical configs must
    land on shared step builds, not one private compile per recipe."""
    stats = shared_step_builders
    if stats["train_calls"] < 2:
        pytest.skip("needs the smoke matrix to have run in this module")
    assert stats["train_builds"] < stats["train_calls"], stats
    assert stats["eval_builds"] < stats["eval_calls"], stats


def test_aot_compile_budget_not_grown():
    """The smoke matrix (and this PR's bucketed recipes) must not push
    the session's AOT sweep over the tier-1 ceiling."""
    from pytorch_distributed_tpu.analysis import lowering

    lowering.assert_compile_budget()
