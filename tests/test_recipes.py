"""Recipe smoke matrix — the de-facto test the reference ran by hand
(start.sh launches, SURVEY.md §4 item 1), executed on the simulated mesh."""

import numpy as np
import pytest

from pytorch_distributed_tpu.recipes import (
    apex_distributed,
    dataparallel,
    distributed,
    horovod_distributed,
    multiprocessing_distributed,
    tpu_native,
)
from pytorch_distributed_tpu.recipes import distributed_slurm_main

SMOKE_ARGS = [
    "--synthetic",
    "--synthetic-length", "32",
    "-a", "resnet18",
    "--image-size", "32",
    "--num-classes", "4",
    "-b", "16",
    "--epochs", "1",
    "-p", "1",
    "--seed", "0",
]


def _args(tmp_path, extra=()):
    return SMOKE_ARGS + ["--checkpoint-dir", str(tmp_path)] + list(extra)


@pytest.mark.parametrize(
    "recipe",
    [
        dataparallel,
        distributed,
        multiprocessing_distributed,
        apex_distributed,
        horovod_distributed,
        distributed_slurm_main,
        tpu_native,
    ],
    ids=lambda m: m.__name__.rsplit(".", 1)[-1],
)
def test_recipe_trains_one_epoch(recipe, tmp_path, capsys, monkeypatch):
    monkeypatch.chdir(tmp_path)  # recipes with epoch CSVs write into cwd
    best = recipe.main(_args(tmp_path))
    out = capsys.readouterr().out
    assert "Epoch: [0]" in out
    assert "* Acc@1" in out
    assert 0.0 <= best <= 100.0
    assert (tmp_path / "checkpoint.msgpack").exists()


def test_epoch_csv_written_by_dataparallel(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    dataparallel.main(_args(tmp_path))
    csv_path = tmp_path / "dataparallel.csv"
    assert csv_path.exists()
    lines = csv_path.read_text().strip().splitlines()
    assert lines[0] == "timestamp,epoch_seconds"  # self-describing header
    row = lines[1].split(",")
    assert len(row) == 2 and float(row[1]) > 0


def test_evaluate_flag(tmp_path, capsys):
    best = tpu_native.main(_args(tmp_path, ["-e"]))
    out = capsys.readouterr().out
    assert "* Acc@1" in out and "Epoch: [0]" not in out


def test_lm_generate_recipe(tmp_path, capsys):
    """Serving CLI: train a tiny byte-LM, checkpoint it, sample from the
    checkpoint via the lm_generate recipe (tokens + decoded text)."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.models.transformer import TransformerLM
    from pytorch_distributed_tpu.recipes import lm_generate
    from pytorch_distributed_tpu.train.checkpoint import save_checkpoint
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState

    cfg = dict(vocab_size=256, d_model=32, n_heads=4, n_layers=2)
    model = TransformerLM(**cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    state = TrainState.create({"params": params}, sgd_init(params))
    path = save_checkpoint(str(tmp_path), state, epoch=3,
                           arch="transformer_lm", best_acc1=0.0,
                           is_best=False)

    rc = lm_generate.main([
        "--resume", path, "--vocab", "256", "--d-model", "32",
        "--n-heads", "4", "--n-layers", "2", "--prompt", "ab",
        "-n", "4", "--temperature", "1.0", "--top-k", "5", "--top-p",
        "0.9", "--seed", "1",
    ])
    outp = capsys.readouterr().out
    assert rc == 0
    assert "epoch 3" in outp and "tokens:" in outp and "text:" in outp

    # --random-init smoke with explicit token ids, no checkpoint
    rc2 = lm_generate.main([
        "--random-init", "--vocab", "64", "--d-model", "32", "--n-heads",
        "4", "--n-layers", "2", "--prompt-tokens", "1,2,3", "-n", "3",
    ])
    assert rc2 == 0


def test_lm_generate_speculative(capsys):
    """--spec-draft: the greedy speculative stream through the CLI equals
    the CLI's own target-only greedy stream (output-distribution identity
    at temperature 0), and the stats line is printed."""
    from pytorch_distributed_tpu.recipes import lm_generate

    base = ["--random-init", "--vocab", "64", "--d-model", "32",
            "--n-heads", "4", "--n-layers", "2", "--prompt-tokens",
            "1,2,3", "-n", "8", "--seed", "3"]
    assert lm_generate.main(base) == 0
    want = capsys.readouterr().out
    assert lm_generate.main(
        base + ["--spec-draft", "random", "--spec-gamma", "2"]) == 0
    got = capsys.readouterr().out
    assert "speculative:" in got and "tok/pass" in got
    tok = [ln for ln in want.splitlines() if ln.startswith("tokens:")]
    assert tok and tok[0] in got
