"""DataLoader / DeviceFeeder behavior on the simulated 8-device mesh."""

import numpy as np
import pytest

from pytorch_distributed_tpu.data import (
    DataLoader,
    DeviceFeeder,
    DistributedShardSampler,
    SyntheticImageDataset,
)
from pytorch_distributed_tpu.parallel import data_parallel_mesh


def _loader(n=24, bsz=8, **kw):
    ds = SyntheticImageDataset(length=n, num_classes=5, image_size=8)
    return DataLoader(ds, batch_size=bsz, sampler=DistributedShardSampler(n, shuffle=False), **kw)


def test_feeder_shards_batches_over_data_axis():
    feeder = DeviceFeeder(data_parallel_mesh())
    batches = list(feeder(iter(_loader())))
    assert len(batches) == 3
    b = batches[0]
    assert b["images"].shape == (8, 8, 8, 3)
    assert b["images"].sharding.spec == (("data",) + b["images"].sharding.spec[1:]) or str(
        b["images"].sharding.spec
    ).startswith("PartitionSpec('data'")


def test_feeder_raises_on_indivisible_batch_in_consumer():
    """Regression: a producer-thread failure must surface at the consumer,
    not silently truncate the epoch (found by verification probe)."""
    feeder = DeviceFeeder(data_parallel_mesh())
    with pytest.raises(ValueError, match="must divide"):
        next(iter(feeder(iter(_loader(bsz=12)))))


def test_feeder_early_exit_stops_producer_thread():
    """Breaking out of the epoch loop (or closing the generator) must not
    leave the producer thread blocked on a full prefetch queue."""
    import threading

    before = {t.ident for t in threading.enumerate()}
    feeder = DeviceFeeder(data_parallel_mesh())
    it = feeder(iter(_loader(n=64)))
    next(it)  # producer running, queue filling
    it.close()  # early exit mid-epoch
    leaked = [
        t for t in threading.enumerate()
        if t.ident not in before and t.is_alive()
    ]
    for t in leaked:
        t.join(timeout=5.0)
    assert not any(t.is_alive() for t in leaked)


def test_final_batch_padding_and_mask():
    loader = _loader(n=20, bsz=8)  # 3 batches, last has 4 real samples
    batches = list(iter(loader))
    assert len(batches) == 3
    assert batches[-1]["weights"].tolist() == [1, 1, 1, 1, 0, 0, 0, 0]
    # Padding slots are zeros, not garbage.
    assert np.all(batches[-1]["images"][4:] == 0)


def test_epoch_changes_augmentation_not_content_order():
    ds = SyntheticImageDataset(length=8, num_classes=5, image_size=8)
    sampler = DistributedShardSampler(8, shuffle=False)
    loader = DataLoader(ds, batch_size=8, sampler=sampler)
    loader.set_epoch(0)
    b0 = next(iter(loader))
    loader.set_epoch(1)
    b1 = next(iter(loader))
    # No transform ⇒ identical content regardless of epoch.
    np.testing.assert_array_equal(b0["images"], b1["images"])
    np.testing.assert_array_equal(b0["labels"], b1["labels"])


def test_transform_rng_varies_by_epoch():
    from pytorch_distributed_tpu.data.transforms import train_transform

    ds = SyntheticImageDataset(
        length=8, num_classes=5, image_size=32, transform=train_transform(size=16)
    )
    sampler = DistributedShardSampler(8, shuffle=False)
    loader = DataLoader(ds, batch_size=8, sampler=sampler)
    loader.set_epoch(0)
    b0 = next(iter(loader))
    loader.set_epoch(1)
    b1 = next(iter(loader))
    assert not np.array_equal(b0["images"], b1["images"])


def test_process_workers_match_thread_workers():
    """worker_type='process' (spawn pool, GIL-proof PIL path) must produce
    byte-identical batches to the thread pool — same per-sample RNG keys."""
    from pytorch_distributed_tpu.data.transforms import train_transform

    ds = SyntheticImageDataset(
        length=20, num_classes=5, image_size=32,
        transform=train_transform(size=16),
    )
    batches = {}
    for wt in ("thread", "process"):
        sampler = DistributedShardSampler(20, shuffle=True, seed=3)
        loader = DataLoader(ds, batch_size=8, sampler=sampler,
                            num_workers=2, worker_type=wt)
        loader.set_epoch(1)
        batches[wt] = list(loader)
    assert len(batches["thread"]) == len(batches["process"])
    for a, b in zip(batches["thread"], batches["process"]):
        np.testing.assert_array_equal(a["images"], b["images"])
        np.testing.assert_array_equal(a["labels"], b["labels"])
        np.testing.assert_array_equal(a["weights"], b["weights"])
