"""Fault-tolerance subsystem (ft/): integrity sidecars + fallback,
in-graph non-finite skip, divergence rollback policy (unit + live LM
trainer), step-granular save/resume parity, chaos injector determinism,
and the chaoskit selftest — the tier-1 fast half of ISSUE 4 (subprocess
kill-and-resume and the rank-kill mesh test live in test_preempt.py,
marked slow)."""

import json
import os
import signal
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.ft import (
    ChaosSchedule,
    CheckpointCorruptError,
    DivergenceGuard,
    LRSpikeAt,
    NaNBatchAt,
    SignalAt,
    corrupt_file,
    retrying,
    sidecar_path,
    verify_sidecar,
    write_sidecar,
)
from pytorch_distributed_tpu.models.transformer import TransformerLM
from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
from pytorch_distributed_tpu.train.checkpoint import (
    CHECKPOINT_NAME,
    PREV_NAME,
    load_checkpoint,
    save_checkpoint,
)
from pytorch_distributed_tpu.train.lm import (
    LMTrainer,
    SyntheticTokenDataset,
    make_lm_train_step,
)
from pytorch_distributed_tpu.train.optim import sgd_init
from pytorch_distributed_tpu.train.state import TrainState
from pytorch_distributed_tpu.utils.preempt import parse_signals

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --------------------------------------------------------------- integrity
def test_sidecar_round_trip_and_corruption_detection(tmp_path):
    p = str(tmp_path / "blob.bin")
    with open(p, "wb") as f:
        f.write(bytes(range(256)) * 8)
    assert verify_sidecar(p) is None  # no sidecar yet: legacy/unverified
    write_sidecar(p)
    assert verify_sidecar(p) is True
    corrupt_file(p, mode="flip", seed=1)
    assert verify_sidecar(p) is False
    # Truncation is caught too (a different failure signature).
    p2 = str(tmp_path / "blob2.bin")
    with open(p2, "wb") as f:
        f.write(bytes(range(256)) * 8)
    write_sidecar(p2)
    corrupt_file(p2, mode="truncate", seed=1)
    assert verify_sidecar(p2) is False


def test_corrupt_file_is_seed_deterministic(tmp_path):
    a, b = str(tmp_path / "a"), str(tmp_path / "b")
    for p in (a, b):
        with open(p, "wb") as f:
            f.write(b"\x00" * 1024)
    ia = corrupt_file(a, mode="flip", seed=42, nbytes=4)
    ib = corrupt_file(b, mode="flip", seed=42, nbytes=4)
    assert ia == ib
    with open(a, "rb") as fa, open(b, "rb") as fb:
        assert fa.read() == fb.read()
    # A different seed must hit different offsets (same size file).
    ic = corrupt_file(b, mode="flip", seed=43, nbytes=4)
    assert ic["offsets"] != ia["offsets"] or ic["masks"] != ia["masks"]


def test_retrying_backoff_and_exhaustion():
    calls, delays = {"n": 0}, []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    assert retrying(flaky, attempts=4, base_delay=0.01,
                    sleep=delays.append) == "ok"
    assert calls["n"] == 3
    assert delays == [0.01, 0.02]  # bounded exponential backoff

    def always():
        raise OSError("persistent")

    with pytest.raises(OSError, match="persistent"):
        retrying(always, attempts=2, base_delay=0.0, sleep=lambda _s: None)

    # Non-retryable exceptions propagate immediately (corruption is not a
    # transient filesystem condition).
    def corrupt():
        calls["n"] += 100
        raise CheckpointCorruptError("bad")

    calls["n"] = 0
    with pytest.raises(CheckpointCorruptError):
        retrying(corrupt, attempts=3, base_delay=0.0, sleep=lambda _s: None)
    assert calls["n"] == 100  # exactly one attempt


def test_parse_signals():
    assert parse_signals("term") == (signal.SIGTERM,)
    assert parse_signals("term,int") == (signal.SIGTERM, signal.SIGINT)
    assert parse_signals("SIGUSR1") == (signal.SIGUSR1,)
    assert parse_signals(str(int(signal.SIGTERM))) == (signal.SIGTERM,)
    with pytest.raises(ValueError, match="SIGKILL"):
        parse_signals("term,kill")
    with pytest.raises(ValueError, match="unknown signal"):
        parse_signals("notasignal")
    with pytest.raises(ValueError, match="no signals"):
        parse_signals(" , ")


# ----------------------------------------------------- checkpoint contract
def _lm_state(seed=0):
    model = TransformerLM(vocab_size=32, d_model=32, n_heads=2, n_layers=1)
    params = model.init(jax.random.PRNGKey(seed),
                        jnp.zeros((1, 16), jnp.int32))["params"]
    return TrainState.create({"params": params}, sgd_init(params))


def test_checkpoint_rotation_sidecars_and_ft_round_trip(tmp_path):
    state = _lm_state()
    d = str(tmp_path)
    ft = {"step": 7, "global_step": 107, "sampler_seed": 3,
          "sampler_epoch": 2, "lr_scale": 0.25}
    save_checkpoint(d, state, epoch=2, arch="transformer_lm",
                    best_acc1=1.5, is_best=False, ft=ft)
    save_checkpoint(d, state, epoch=3, arch="transformer_lm",
                    best_acc1=1.5, is_best=False)
    latest = os.path.join(d, CHECKPOINT_NAME)
    prev = os.path.join(d, PREV_NAME)
    # Retain-2 rotation, both files independently verifiable.
    assert verify_sidecar(latest) is True
    assert verify_sidecar(prev) is True
    assert not os.path.exists(latest + ".tmp")
    _, meta = load_checkpoint(prev, _lm_state(seed=1))
    assert meta["epoch"] == 2
    assert meta["ft"] == ft  # the step-granular record round-trips
    _, meta = load_checkpoint(latest, _lm_state(seed=1))
    assert meta["epoch"] == 3
    assert meta["ft"]["step"] == 0  # no ft passed → epoch-boundary defaults


@pytest.mark.parametrize("mode", ["flip", "truncate"])
def test_corrupt_latest_falls_back_to_prev(tmp_path, mode):
    state = _lm_state()
    d = str(tmp_path)
    save_checkpoint(d, state, epoch=5, arch="transformer_lm",
                    best_acc1=0.0, is_best=False,
                    ft={"step": 2, "global_step": 2})
    save_checkpoint(d, state, epoch=6, arch="transformer_lm",
                    best_acc1=0.0, is_best=False)
    latest = os.path.join(d, CHECKPOINT_NAME)
    corrupt_file(latest, mode=mode, seed=9)
    with pytest.warns(UserWarning, match="falling back"):
        restored, meta = load_checkpoint(latest, _lm_state(seed=1))
    assert meta["epoch"] == 5  # the retained previous checkpoint
    assert meta["ft"]["step"] == 2
    for a, b in zip(jax.tree_util.tree_leaves(restored.params),
                    jax.tree_util.tree_leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Both corrupt → loud CheckpointCorruptError, no silent garbage.
    corrupt_file(os.path.join(d, PREV_NAME), mode=mode, seed=9)
    with pytest.raises(CheckpointCorruptError):
        with pytest.warns(UserWarning, match="falling back"):
            load_checkpoint(latest, _lm_state(seed=1))


def test_legacy_checkpoint_without_sidecar_still_loads(tmp_path):
    """Pre-FT payload layout (no 'ft' key, no sidecar) must keep loading:
    checkpoints written before this subsystem existed stay resumable."""
    from flax import serialization

    state = _lm_state()
    payload = {
        "epoch": 4, "arch": "transformer_lm", "best_acc1": 2.5,
        "state": {
            "step": np.asarray(state.step),
            "params": jax.device_get(state.params),
            "batch_stats": {},
            "momentum": jax.device_get(state.momentum),
        },
    }
    p = str(tmp_path / "legacy.msgpack")
    with open(p, "wb") as f:
        f.write(serialization.to_bytes(payload))
    restored, meta = load_checkpoint(p, _lm_state(seed=1))
    assert meta["epoch"] == 4 and meta["best_acc1"] == 2.5
    assert meta["ft"]["step"] == 0 and meta["ft"]["lr_scale"] == 1.0
    # ... and a corrupted legacy file is reported as corruption, not a
    # cryptic msgpack unpack error.
    corrupt_file(p, mode="truncate", seed=2)
    with pytest.raises(CheckpointCorruptError):
        load_checkpoint(p, _lm_state(seed=1))


def test_model_best_written_atomically_with_sidecar(tmp_path):
    from pytorch_distributed_tpu.train.checkpoint import BEST_NAME

    state = _lm_state()
    save_checkpoint(str(tmp_path), state, 0, "transformer_lm", 1.0,
                    is_best=True)
    best = str(tmp_path / BEST_NAME)
    assert os.path.exists(best)
    assert not os.path.exists(best + ".tmp")
    assert verify_sidecar(best) is True


# ----------------------------------------------- in-graph non-finite guard
def test_lm_step_nonfinite_flag_gates_update():
    mesh = build_mesh(MeshSpec(("data",), (jax.device_count(),)))
    model = TransformerLM(vocab_size=32, d_model=32, n_heads=2, n_layers=1)
    with mesh:
        state = _lm_state()
        from pytorch_distributed_tpu.parallel.tp import replicated_like

        step = make_lm_train_step(model, mesh,
                                  replicated_like(state.params),
                                  guard_nonfinite=True)
        tokens = jnp.zeros((8, 16), jnp.int32)
        lr = jnp.float32(0.1)
        # Clean state: flag 0, params move.
        new_state, metrics = step(state, tokens, lr)
        assert float(metrics["nonfinite"]) == 0.0
        moved = any(
            not np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(jax.tree_util.tree_leaves(_lm_state().params),
                            jax.tree_util.tree_leaves(new_state.params)))
        assert moved
        # Poisoned params: loss goes NaN → flag 1, the whole update
        # (params AND momentum) passes through unchanged except the step
        # counter — NaN never propagates into the momentum buffers.
        bad = _lm_state()
        poisoned = jax.tree_util.tree_map(
            lambda p: p.at[(0,) * p.ndim].set(jnp.nan), bad.params)
        bad = TrainState(bad.step, poisoned, bad.batch_stats, bad.momentum)
        momentum_before = jax.device_get(bad.momentum)
        out_state, metrics = step(bad, tokens, lr)
        assert float(metrics["nonfinite"]) == 1.0
        assert int(out_state.step) == 1  # step counter still advances
        for a, b in zip(jax.tree_util.tree_leaves(momentum_before),
                        jax.tree_util.tree_leaves(out_state.momentum)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------------------- divergence guard policy
def test_divergence_guard_policy_and_events(tmp_path):
    from pytorch_distributed_tpu.obs import MetricsLogger

    mpath = str(tmp_path / "m.jsonl")
    obs = MetricsLogger(mpath)
    guard = DivergenceGuard(rollback_k=2, check_every=3, lr_backoff=0.5,
                            obs=obs)
    # Flags buffer lazily: no decision until the 3rd observation drains.
    assert guard.observe(0, 0.0) is False
    assert guard.observe(1, 1.0) is False
    assert guard.observe(2, 1.0) is True  # drain: 2 consecutive ≥ K
    assert guard.skipped == [1, 2]
    assert guard.note_rollback(2, restored_step=0) == 0.5
    assert guard.consecutive == 0 and guard.rollbacks == 1
    # Non-consecutive flags never trip the rollback.
    for step, f in enumerate([1.0, 0.0, 1.0, 0.0, 1.0, 0.0], start=3):
        assert guard.observe(step, f) is False
    assert guard.rollbacks == 1
    obs.close()
    events = [json.loads(ln) for ln in open(mpath) if "ft_event" in ln]
    kinds = [e["ft_event"] for e in events]
    assert kinds.count("rollback") == 1
    assert kinds.count("skip") == len(guard.skipped)
    rb = next(e for e in events if e["ft_event"] == "rollback")
    assert rb["lr_scale"] == 0.5 and rb["restored_step"] == 0


def test_divergence_guard_validates_knobs():
    with pytest.raises(ValueError, match="rollback_k"):
        DivergenceGuard(rollback_k=0)
    with pytest.raises(ValueError, match="lr_backoff"):
        DivergenceGuard(lr_backoff=0.0)


# ------------------------------------------------------------------ chaos
class _FakeTrainer:
    lr = 0.1


def test_chaos_injectors_fire_deterministically():
    t = _FakeTrainer()
    hits = []
    prev = signal.signal(signal.SIGUSR2, lambda s, f: hits.append(s))
    try:
        sched = ChaosSchedule(SignalAt(2, signal.SIGUSR2),
                              LRSpikeAt(1, 123.0))
        for i in range(5):
            sched.on_step(t, i)
            if i == 1:
                assert t.lr == 123.0  # spike applied for exactly one step
        assert t.lr == 0.1            # ... and restored
        assert hits == [signal.SIGUSR2]  # fired once, at step 2 only
    finally:
        signal.signal(signal.SIGUSR2, prev)


def test_nan_batch_injector_poisons_only_float_leaves():
    inj = NaNBatchAt([3], keys=("images",))
    batch = {"images": jnp.ones((2, 4), jnp.float32),
             "labels": jnp.ones((2,), jnp.int32),
             "weights": jnp.ones((2,), jnp.float32)}
    same = inj.on_batch(0, batch)
    assert same is batch  # untouched off-schedule
    out = inj.on_batch(3, batch)
    assert np.isnan(np.asarray(out["images"])).all()
    np.testing.assert_array_equal(np.asarray(out["labels"]),
                                  np.asarray(batch["labels"]))
    np.testing.assert_array_equal(np.asarray(out["weights"]),
                                  np.asarray(batch["weights"]))  # keyed out


# ---------------------------------------------------- live LMTrainer flows
def _lm_trainer(tmp_path, mesh, model, ds, **kw):
    return LMTrainer(model, mesh, ds, batch_size=8, lr=0.05,
                     eval_dataset=None, **kw)


def test_lm_divergence_rollback_recovers_training(tmp_path):
    """An LR spike corrupts the params to non-finite; the guard skips the
    poisoned steps in-graph, rolls back to the last-good snapshot after K
    consecutive flags, backs off the LR, and training recovers to a finite
    loss — the full pillar-2 loop, live."""
    mesh = build_mesh(MeshSpec(("data",), (jax.device_count(),)))
    model = TransformerLM(vocab_size=32, d_model=32, n_heads=2, n_layers=1)
    ds = SyntheticTokenDataset(64, 16, 32)
    mpath = str(tmp_path / "m.jsonl")
    with mesh:
        t = _lm_trainer(tmp_path, mesh, model, ds,
                        nan_guard=True, ft_rollback_k=2, ft_check_every=3,
                        metrics_jsonl=mpath,
                        chaos=ChaosSchedule(LRSpikeAt(2, 1e30)))
        final = t.fit(16, print_freq=8)
    assert t.ft_guard.rollbacks >= 1
    assert t.ft_guard.skipped  # the poisoned steps were gated in-graph
    assert t.ft_guard.lr_scale < 1.0
    assert np.isfinite(final)
    for leaf in jax.tree_util.tree_leaves(jax.device_get(t.state.params)):
        assert np.isfinite(leaf).all()
    events = [json.loads(ln) for ln in open(mpath) if "ft_event" in ln]
    kinds = {e["ft_event"] for e in events}
    assert {"skip", "rollback"} <= kinds


def test_lm_save_steps_preempt_resume_parity(tmp_path):
    """Kill-and-resume parity (acceptance criterion): a run preempted
    mid-stream with --save-steps resumes at the exact step and finishes
    with the SAME final parameters and loss as an uninterrupted run."""
    mesh = build_mesh(MeshSpec(("data",), (jax.device_count(),)))
    model = TransformerLM(vocab_size=32, d_model=32, n_heads=2, n_layers=1)
    ds = SyntheticTokenDataset(64, 16, 32)
    d = str(tmp_path / "ckpt")
    with mesh:
        ref = _lm_trainer(tmp_path, mesh, model, ds)
        loss_ref = ref.fit(8, print_freq=4)

        from pytorch_distributed_tpu.utils.preempt import PreemptionGuard

        guard = PreemptionGuard(signals=(signal.SIGUSR1,)).install()
        try:
            t1 = _lm_trainer(tmp_path, mesh, model, ds,
                             checkpoint_dir=d, save_steps=2, preempt=guard,
                             chaos=ChaosSchedule(
                                 SignalAt(4, signal.SIGUSR1)))
            t1.fit(8, print_freq=1)
        finally:
            guard.uninstall()
        stop = int(t1.state.step)
        assert 0 < stop < 8  # genuinely interrupted mid-stream
        ckpt = os.path.join(d, CHECKPOINT_NAME)
        _, meta = load_checkpoint(ckpt, _lm_state(seed=1))
        assert meta["ft"]["global_step"] == stop

        t2 = _lm_trainer(tmp_path, mesh, model, ds,
                         checkpoint_dir=d, resume=ckpt)
        assert t2._start_step == stop  # exact step restored, no rerun
        loss2 = t2.fit(8, print_freq=4)
    assert loss2 == pytest.approx(loss_ref, rel=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(ref.state.params)),
                    jax.tree_util.tree_leaves(jax.device_get(t2.state.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_lm_resume_falls_back_when_latest_corrupt(tmp_path):
    """The end-to-end storage-failure story: the newest checkpoint is
    bit-flipped on disk; --resume detects it via the sidecar, falls back
    to checkpoint.prev.msgpack, and continues from that step."""
    mesh = build_mesh(MeshSpec(("data",), (jax.device_count(),)))
    model = TransformerLM(vocab_size=32, d_model=32, n_heads=2, n_layers=1)
    ds = SyntheticTokenDataset(64, 16, 32)
    d = str(tmp_path / "ckpt")
    with mesh:
        t1 = _lm_trainer(tmp_path, mesh, model, ds,
                         checkpoint_dir=d, save_steps=3)
        t1.fit(8, print_freq=4)  # cadence saves at 3, 6; final at 8
        ckpt = os.path.join(d, CHECKPOINT_NAME)
        corrupt_file(ckpt, mode="flip", seed=4)
        with pytest.warns(UserWarning, match="falling back"):
            t2 = _lm_trainer(tmp_path, mesh, model, ds,
                             checkpoint_dir=d, resume=ckpt)
        assert t2._start_step == 6  # the retained previous (cadence) save
        loss = t2.fit(8, print_freq=4)
    assert np.isfinite(loss)


# --------------------------------------------------------------- chaoskit
def test_chaoskit_cli_selftest_runs_clean():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "chaoskit.py"),
         "--selftest"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "chaoskit selftest: OK" in out.stdout


def test_chaoskit_cli_verify_detects_corruption(tmp_path):
    p = str(tmp_path / "c.msgpack")
    with open(p, "wb") as f:
        f.write(b"payload" * 64)
    kit = os.path.join(REPO, "scripts", "chaoskit.py")
    run = lambda *a: subprocess.run(  # noqa: E731
        [sys.executable, kit, *a], capture_output=True, text=True,
        timeout=120)
    assert run("seal", p).returncode == 0
    assert run("verify", p).returncode == 0
    assert run("corrupt", p, "--seed", "11").returncode == 0
    r = run("verify", p)
    assert r.returncode == 1 and "CORRUPT" in r.stdout
