"""Unit tests for meters, LR schedule, and torch-parity SGD."""

import numpy as np
import pytest

from pytorch_distributed_tpu.train import (
    AverageMeter,
    ProgressMeter,
    sgd_init,
    sgd_update,
    step_decay_lr,
)


def test_average_meter_running_stats():
    m = AverageMeter("Loss", ":.4e")
    m.update(2.0, n=4)
    m.update(1.0, n=4)
    assert m.val == 1.0
    assert m.avg == pytest.approx(1.5)
    assert m.count == 8


def test_average_meter_defers_conversion():
    import jax.numpy as jnp

    m = AverageMeter("Acc@1", ":6.2f")
    m.update(jnp.float32(50.0), n=2)  # device scalar accepted lazily
    assert m.avg == pytest.approx(50.0)
    assert "Acc@1" in str(m)


def test_progress_meter_row_format():
    m = AverageMeter("Time", ":6.3f")
    m.update(0.5)
    p = ProgressMeter(100, [m], prefix="Epoch: [3]")
    line = p.display(7)
    assert line.startswith("Epoch: [3][  7/100]")
    assert "Time" in line


def test_step_decay_matches_reference_formula():
    # reference distributed.py:374-378: lr = lr0 * 0.1 ** (epoch // 30)
    for epoch, want in [(0, 0.1), (29, 0.1), (30, 0.01), (59, 0.01), (60, 0.001)]:
        assert step_decay_lr(0.1, epoch) == pytest.approx(want)


def test_sgd_matches_torch_semantics():
    """Three steps with an LR change mid-momentum must match torch.optim.SGD."""
    torch = pytest.importorskip("torch")
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    w0 = rng.normal(size=(5, 3)).astype(np.float32)
    grads = [rng.normal(size=(5, 3)).astype(np.float32) for _ in range(3)]
    lrs = [0.1, 0.1, 0.01]
    mu, wd = 0.9, 1e-4

    # torch oracle
    wt = torch.nn.Parameter(torch.from_numpy(w0.copy()))
    opt = torch.optim.SGD([wt], lr=lrs[0], momentum=mu, weight_decay=wd)
    for g, lr in zip(grads, lrs):
        for group in opt.param_groups:
            group["lr"] = lr
        opt.zero_grad()
        wt.grad = torch.from_numpy(g.copy())
        opt.step()

    # ours
    params = {"w": jnp.asarray(w0)}
    buf = sgd_init(params)
    for g, lr in zip(grads, lrs):
        params, buf = sgd_update(
            {"w": jnp.asarray(g)}, buf, params, lr, momentum=mu, weight_decay=wd
        )

    np.testing.assert_allclose(
        np.asarray(params["w"]), wt.detach().numpy(), rtol=1e-5, atol=1e-6
    )


def test_sgd_update_inside_jit():
    import jax
    import jax.numpy as jnp

    params = {"a": jnp.ones((4,)), "b": {"c": jnp.full((2, 2), 2.0)}}
    buf = sgd_init(params)
    grads = jax.tree_util.tree_map(jnp.ones_like, params)

    @jax.jit
    def step(p, b, g, lr):
        return sgd_update(g, b, p, lr)

    p2, b2 = step(params, buf, grads, 0.5)
    assert jax.tree_util.tree_structure(p2) == jax.tree_util.tree_structure(params)
    assert np.asarray(p2["a"]).shape == (4,)


def test_telemetry_reports_real_bytes_without_memory_stats(tmp_path):
    """VERDICT weak #6: on platforms without device memory_stats the CSV
    must still carry REAL buffer bytes (client-side live_arrays accounting),
    not zeroed columns."""
    import csv

    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.utils.telemetry import sample_devices

    keep = jnp.ones((256, 1024), jnp.float32)  # ~1MB live on device 0
    rows = sample_devices()
    assert len(rows) == len(jax.local_devices())
    total_in_use = sum(r[3] for r in rows)
    assert total_in_use >= keep.nbytes  # real bytes, not zeros
    # peak tracks at least the current in-use
    assert all(r[4] >= r[3] or r[2] > 0 for r in rows)
    del keep


def test_measure_train_step_and_oom_heuristic():
    """Shared bench harness (utils/benchstep.py): measures a real compiled
    step with the value-fetch barrier; the OOM heuristic separates
    capacity failures (halve and retry) from deterministic ones."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_tpu import models
    from pytorch_distributed_tpu.parallel import data_parallel_mesh
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState
    from pytorch_distributed_tpu.train.steps import make_train_step
    from pytorch_distributed_tpu.utils.benchstep import (
        looks_like_oom,
        measure_train_step,
    )

    mesh = data_parallel_mesh()
    rng = np.random.default_rng(0)
    batch = {
        "images": jnp.asarray(rng.normal(size=(8, 32, 32, 3)),
                              dtype=jnp.float32),
        "labels": jnp.asarray(rng.integers(0, 10, size=8).astype(np.int32)),
        "weights": jnp.ones((8,), jnp.float32),
    }
    model = models.create_model("squeezenet1_1", num_classes=10)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 32, 32, 3)), train=False)
    state = TrainState.create(variables, sgd_init(variables["params"]))
    step = make_train_step(model, mesh)
    dt, new_state = measure_train_step(step, state, batch, jnp.float32(0.1),
                                       iters=2, warmup=1)
    assert dt > 0
    assert int(new_state.step) == 3  # warmup + timed iters all executed

    assert looks_like_oom(RuntimeError("RESOURCE_EXHAUSTED: ..."))
    assert looks_like_oom(MemoryError("Out of memory allocating 1GB"))
    assert not looks_like_oom(ValueError("unknown arch 'resnet999'"))
