"""Comm-overlap scheduler (parallel/overlap.py, ``--overlap bucketed``).

The tier-1 pins behind the ISSUE-16 contract — bucketing is a pure
*schedule* transformation, so every numerics assertion here is bit-exact:

- bucket planning: reverse-autodiff partition covers each leaf exactly
  once, respects the byte cap, and degenerates to one bucket when the
  cap exceeds the gradient;
- bucketed ≡ monolithic on the explicit image step for f32 / bf16 wire
  and for int8 error-feedback (3-step training parity, grads + params +
  loss identical to the last bit);
- same on the explicit shard_map LM step (f32 and int8-EF), plus the
  GSPMD cross-check at f32 tolerance;
- ZeRO-WUS: bucketed delta all-gather ≡ monolithic gather, and the
  double-buffered (``wus_gather="deferred"``) params materialize to the
  eager run's params exactly;
- mode/flag validation: the scheduler refuses the combinations it cannot
  keep bit-exact (GSPMD step, deferred+quantized, LM+wus, elastic);
- ledger attribution: compiled bucketed collectives carry ``b<k>``
  labels and per-phase byte totals still sum to the monolithic budget.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.models.transformer import TransformerLM
from pytorch_distributed_tpu.obs import comms
from pytorch_distributed_tpu.ops import qcomm
from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
from pytorch_distributed_tpu.parallel import overlap as overlap_lib
from pytorch_distributed_tpu.parallel import zero as zero_lib
from pytorch_distributed_tpu.parallel.tp import replicated_like
from pytorch_distributed_tpu.train.lm import LMTrainer, make_lm_train_step
from pytorch_distributed_tpu.train.optim import sgd_init
from pytorch_distributed_tpu.train.state import TrainState
from pytorch_distributed_tpu.train.steps import make_train_step

from tests.test_steps import _MLP, _leaves_allclose

N = 4


def _mesh4():
    return build_mesh(MeshSpec(("data",), (N,)), jax.devices()[:N])


def _leaves_equal(a, b):
    fa, fb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(fa) == len(fb)
    for x, y in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ------------------------------------------------------- bucket planning


def test_plan_buckets_partitions_in_reverse_autodiff_order():
    tree = {"a": jnp.zeros((256,)), "b": jnp.zeros((512,)),
            "c": jnp.zeros((64,))}
    leaves = jax.tree_util.tree_leaves(tree)
    buckets = overlap_lib.plan_buckets(tree, bucket_mb=1 / 1024)  # 1 KiB cap
    # exact partition of leaf indices
    flat = [i for b in buckets for i in b]
    assert sorted(flat) == list(range(len(leaves)))
    # reverse-autodiff order: bucket 0 starts from the LAST flatten leaf
    assert flat == list(reversed(range(len(leaves))))
    # byte cap: every bucket but the terminal one closed at/over 1 KiB
    for b in buckets[:-1]:
        assert sum(leaves[i].size * 4 for i in b) >= 1024


def test_plan_buckets_degenerates_to_one_bucket():
    tree = [jnp.zeros((8,)), jnp.zeros((8,))]
    assert overlap_lib.plan_buckets(tree, bucket_mb=64.0) == [[1, 0]]
    assert overlap_lib.n_buckets(tree, bucket_mb=64.0) == 1


def test_resolve_overlap_validates():
    assert overlap_lib.resolve_overlap("none") == "none"
    assert overlap_lib.resolve_overlap("bucketed") == "bucketed"
    with pytest.raises(ValueError):
        overlap_lib.resolve_overlap("eager")


# ------------------------------------- explicit image step: bucketed ≡ mono


def _image_setup(seed=0):
    model = _MLP(classes=10)
    variables = model.init(jax.random.PRNGKey(seed),
                           jnp.zeros((1, 8, 8, 3)))
    rng = np.random.default_rng(seed)
    batch = {
        "images": rng.normal(size=(16, 8, 8, 3)).astype(np.float32),
        "labels": rng.integers(0, 10, size=16).astype(np.int32),
        "weights": np.ones(16, np.float32),
    }
    return model, variables, batch


def _run_image(model, variables, batch, mesh, n_steps=3, zero="none",
               wus_gather="eager", **kw):
    gc = kw.get("grad_compress", "none")
    quantized = gc in qcomm.QUANTIZED_MODES
    # fresh param buffers per run: the jitted step donates its state
    params = jax.tree_util.tree_map(
        lambda x: jnp.array(np.asarray(x)), variables["params"])
    residual = qcomm.init_residual(params, gc, explicit=True, n_data=N)
    if zero == "wus":
        mom = zero_lib.init_wus_momentum(params, N, quantized=quantized)
        if wus_gather == "deferred":
            mom["pending"] = overlap_lib.init_pending(params, N)
    else:
        mom = sgd_init(params)
    state = TrainState.create({"params": params}, mom, residual=residual)
    step = make_train_step(model, mesh, explicit_collectives=True,
                           zero=zero, wus_gather=wus_gather, **kw)
    for _ in range(n_steps):
        state, metrics = step(state, batch, jnp.float32(0.1))
    return state, metrics


@pytest.mark.parametrize("gc", ["none", "bf16", "int8"])
def test_image_bucketed_matches_monolithic_bitexact(gc):
    mesh = _mesh4()
    model, variables, batch = _image_setup()
    s0, m0 = _run_image(model, variables, batch, mesh, grad_compress=gc)
    s1, m1 = _run_image(model, variables, batch, mesh, grad_compress=gc,
                        overlap="bucketed", bucket_mb=0.001)
    _leaves_equal(s0.params, s1.params)
    if gc == "int8":  # error-feedback state must track bit-exactly too
        _leaves_equal(s0.residual, s1.residual)
    assert float(m0["loss"]) == float(m1["loss"])


# ------------------------------------------- ZeRO-WUS bucketed + deferred


@pytest.mark.parametrize("gc", ["none", "int8"])
def test_wus_bucketed_gather_matches_monolithic(gc):
    mesh = _mesh4()
    model, variables, batch = _image_setup()
    s0, _ = _run_image(model, variables, batch, mesh, zero="wus",
                       grad_compress=gc)
    s1, _ = _run_image(model, variables, batch, mesh, zero="wus",
                       grad_compress=gc, overlap="bucketed",
                       bucket_mb=0.001)
    _leaves_equal(s0.params, s1.params)
    _leaves_equal(s0.momentum["buf"], s1.momentum["buf"])


def test_wus_deferred_materializes_to_eager_params():
    """Double-buffered delta all-gather: the live params lag by one
    pending delta; replaying the wire cast on the host recovers the
    eager run's params to the last bit."""
    mesh = _mesh4()
    model, variables, batch = _image_setup()
    s_eager, _ = _run_image(model, variables, batch, mesh, zero="wus",
                            overlap="bucketed", bucket_mb=0.001)
    s_def, _ = _run_image(model, variables, batch, mesh, zero="wus",
                          wus_gather="deferred", overlap="bucketed",
                          bucket_mb=0.001)
    mat = overlap_lib.materialize_params(
        jax.device_get(s_def.params),
        jax.device_get(s_def.momentum["pending"]))
    _leaves_equal(s_eager.params, mat)


# -------------------------------------------------- explicit LM shard_map


@pytest.fixture(scope="module")
def lm_setup():
    mesh = _mesh4()
    model = TransformerLM(vocab_size=64, d_model=32, n_heads=4, n_layers=1)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(8, 16)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    return mesh, model, tokens, params


def _run_lm(lm_setup, n_steps=3, **kw):
    mesh, model, tokens, params = lm_setup
    gc = kw.get("grad_compress", "none")
    explicit = (kw.get("explicit_collectives", False)
                or kw.get("overlap") == "bucketed")
    p0 = jax.tree_util.tree_map(
        lambda x: jnp.array(np.asarray(x)), params)
    residual = qcomm.init_residual(p0, gc, explicit=explicit, n_data=N)
    state = TrainState.create({"params": p0}, sgd_init(p0),
                              residual=residual)
    step = make_lm_train_step(model, mesh, replicated_like(p0), **kw)
    for _ in range(n_steps):
        state, metrics = step(state, tokens, jnp.float32(0.1))
    return state, metrics


@pytest.mark.parametrize("gc", ["none", "int8"])
def test_lm_bucketed_matches_monolithic_bitexact(lm_setup, gc):
    s0, m0 = _run_lm(lm_setup, explicit_collectives=True, grad_compress=gc)
    s1, m1 = _run_lm(lm_setup, overlap="bucketed", bucket_mb=0.001,
                     grad_compress=gc)
    _leaves_equal(s0.params, s1.params)
    assert float(m0["loss"]) == float(m1["loss"])


def test_lm_explicit_tracks_gspmd(lm_setup):
    """The explicit shard_map step is a different lowering of the same
    math as the GSPMD step — equal to f32 reduction-order tolerance."""
    sg, mg = _run_lm(lm_setup)
    se, me = _run_lm(lm_setup, overlap="bucketed", bucket_mb=0.001)
    _leaves_allclose(sg.params, se.params, rtol=0, atol=1e-5)
    assert abs(float(mg["loss"]) - float(me["loss"])) < 1e-5


# ------------------------------------------------------------- validation


def test_bucketed_requires_explicit_collectives():
    mesh = _mesh4()
    model = _MLP(classes=10)
    with pytest.raises(ValueError, match="explicit"):
        make_train_step(model, mesh, overlap="bucketed")


def test_deferred_gather_requires_wus_and_bucketed():
    mesh = _mesh4()
    model = _MLP(classes=10)
    with pytest.raises(ValueError, match="deferred"):
        make_train_step(model, mesh, explicit_collectives=True,
                        wus_gather="deferred")


def test_deferred_gather_rejects_quantized_wire():
    mesh = _mesh4()
    model = _MLP(classes=10)
    with pytest.raises(ValueError, match="quantiz"):
        make_train_step(model, mesh, explicit_collectives=True,
                        zero="wus", overlap="bucketed",
                        wus_gather="deferred", grad_compress="int8")


def test_lm_bucketed_rejects_wus(lm_setup):
    mesh, model, tokens, params = lm_setup
    with pytest.raises(ValueError, match="zero"):
        make_lm_train_step(model, mesh, replicated_like(params),
                           overlap="bucketed", zero="wus", params=params)


def test_lm_trainer_rejects_bucketed_with_elastic():
    from pytorch_distributed_tpu.train.lm import SyntheticTokenDataset

    mesh = _mesh4()
    model = TransformerLM(vocab_size=32, d_model=32, n_heads=2, n_layers=1)
    ds = SyntheticTokenDataset(64, 16, 32)
    with pytest.raises(ValueError, match="elastic"):
        LMTrainer(model, mesh, ds, batch_size=8, overlap="bucketed",
                  elastic=object())


def test_lm_trainer_bucketed_int8_evaluate():
    """Regression: the eval step's in_shardings must cover the explicit
    path's stacked per-rank residual (P("data")), not the param-shaped
    emulation layout — evaluate() under overlap='bucketed' +
    grad_compress='int8' used to raise a pjit sharding mismatch."""
    import math

    from pytorch_distributed_tpu.train.lm import SyntheticTokenDataset

    mesh = _mesh4()
    model = TransformerLM(vocab_size=32, d_model=32, n_heads=2, n_layers=1)
    ds = SyntheticTokenDataset(64, 16, 32)
    tr = LMTrainer(model, mesh, ds, batch_size=8, overlap="bucketed",
                   grad_compress="int8", eval_dataset=ds, eval_batches=1)
    loss, ppl, acc = tr.evaluate()
    assert math.isfinite(loss) and ppl > 0 and 0.0 <= acc <= 100.0


# --------------------------------------------------- ledger attribution


def test_bucket_of_op_name():
    f = comms.bucket_of_op_name
    assert f("jit(step)/grad_sync/b3/psum") == 3
    assert f("transpose(jvp(_MLP))/grad_sync/b0") == 0
    assert f("optimizer/ag_b2/all_gather") == 2
    assert f("jit(step)/grad_sync/psum") == -1
    assert f("bucket12/x") == -1  # only the exact b<k> scope counts
    assert f("") == -1


def test_compiled_buckets_sum_to_monolithic_budget(get_lowering):
    """Bucketing relabels collectives within grad_sync — it must not move
    or create bytes: per-phase totals equal the monolithic explicit
    twin's, and every gradient collective carries a bucket label."""
    mono = comms.ledger_from_hlo_text(
        get_lowering("train_image_explicit").text, step="mono")
    bucketed = comms.ledger_from_hlo_text(
        get_lowering("train_image_bucketed").text, step="bucketed")

    assert (bucketed.by_phase()["grad_sync"]["bytes"]
            == mono.by_phase()["grad_sync"]["bytes"])
    assert bucketed.total_bytes == mono.total_bytes

    grad_entries = [e for e in bucketed.entries if e.phase == "grad_sync"]
    labeled = {e.bucket for e in grad_entries if e.bucket >= 0}
    assert len(labeled) >= 2, [e.op_name for e in grad_entries]
    # monolithic twin has no bucket labels at all
    assert all(e.bucket == -1 for e in mono.entries)


def test_ledger_json_roundtrips_bucket_field(tmp_path, get_lowering):
    lg = comms.ledger_from_hlo_text(
        get_lowering("train_image_bucketed").text,
        step="train_image_bucketed")
    path = str(tmp_path / "comm_ledger.json")
    comms.write_ledgers(path, [lg])
    loaded = comms.load_ledgers(path)["train_image_bucketed"]
    assert ([e.bucket for e in loaded.entries]
            == [e.bucket for e in lg.entries])

    # legacy payload without the field loads with the -1 default
    import json

    data = json.load(open(path))
    for e in data["train_image_bucketed"]["entries"]:
        e.pop("bucket")
    with open(path, "w") as f:
        json.dump(data, f)
    legacy = comms.load_ledgers(path)["train_image_bucketed"]
    assert {e.bucket for e in legacy.entries} == {-1}


def test_int8_bucketed_lm_wire_is_quantized(get_lowering):
    """The GSPMD-migration acceptance pin: with --overlap bucketed and
    --grad-compress int8 the LM step's compiled gradient collectives
    carry s8 payloads (f32 is scale side-cars only), i.e. compression
    rides the real wire instead of a numerics emulation."""
    lg = comms.ledger_from_hlo_text(
        get_lowering("lm_train_bucketed_int8").text, step="int8")
    enc = lg.phase_wire_encodings("grad_sync")
    assert "int8" in enc, enc
    assert enc["int8"] > 10 * enc.get("f32", 0.0), enc
