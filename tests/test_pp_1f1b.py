"""1F1B pipeline schedule: numerics parity with GPipe + the memory bound.

The 1F1B schedule (parallel/pp_1f1b.py) computes gradients manually inside
its interleaved scan; these tests pin it to the GPipe/autodiff path — same
loss, same accuracy, same updated parameters — and check the stash shape
carries the 2(P-1)+1 bound rather than M slots."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.models.pipeline_lm import (
    PipelinedTransformerLM,
    pp_specs,
)
from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
from pytorch_distributed_tpu.parallel.tp import shard_state
from pytorch_distributed_tpu.train.lm import make_lm_train_step
from pytorch_distributed_tpu.train.optim import sgd_init
from pytorch_distributed_tpu.train.state import TrainState

VOCAB, D, HEADS, LAYERS, STAGES, SEQ, BATCH = 64, 32, 2, 4, 4, 16, 8


def _one_step(schedule, n_micro, tokens, remat=False):
    mesh = build_mesh(MeshSpec(("data", "pipe"), (2, STAGES)),
                      jax.devices()[:2 * STAGES])
    model = PipelinedTransformerLM(
        vocab_size=VOCAB, d_model=D, n_heads=HEADS, n_layers=LAYERS,
        n_stages=STAGES, n_microbatches=n_micro, mesh=mesh,
        schedule=schedule, remat=remat,
    )
    with mesh:
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        spec = pp_specs(params)
        state = shard_state(
            TrainState.create({"params": params}, sgd_init(params)),
            spec, mesh,
        )
        step = make_lm_train_step(model, mesh, spec, weight_decay=0.0)
        from jax.sharding import NamedSharding, PartitionSpec as P

        toks = jax.device_put(tokens, NamedSharding(mesh, P("data", None)))
        new_state, metrics = step(state, toks, jnp.float32(0.05))
        return (
            jax.device_get(new_state.params),
            {k: float(v) for k, v in metrics.items()},
        )


@pytest.mark.parametrize("n_micro", [2, 4])
def test_1f1b_matches_gpipe(n_micro):
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, VOCAB, size=(BATCH, SEQ)).astype(np.int32)

    gp_params, gp_metrics = _one_step("gpipe", n_micro, tokens)
    fb_params, fb_metrics = _one_step("1f1b", n_micro, tokens)

    assert gp_metrics["loss"] == pytest.approx(fb_metrics["loss"], rel=1e-5)
    assert gp_metrics["acc"] == pytest.approx(fb_metrics["acc"], abs=1e-4)
    flat_g = jax.tree_util.tree_leaves_with_path(gp_params)
    flat_f = dict(
        (jax.tree_util.keystr(p), l)
        for p, l in jax.tree_util.tree_leaves_with_path(fb_params)
    )
    for path, leaf in flat_g:
        key = jax.tree_util.keystr(path)
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(flat_f[key]),
            rtol=2e-4, atol=2e-5, err_msg=key)


def test_gpipe_remat_matches_plain():
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, VOCAB, size=(BATCH, SEQ)).astype(np.int32)
    plain_params, plain_metrics = _one_step("gpipe", 2, tokens)
    remat_params, remat_metrics = _one_step("gpipe", 2, tokens, remat=True)
    assert plain_metrics["loss"] == pytest.approx(remat_metrics["loss"],
                                                  rel=1e-6)
    for (pa, la), (pb, lb) in zip(
        jax.tree_util.tree_leaves_with_path(plain_params),
        jax.tree_util.tree_leaves_with_path(remat_params),
    ):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   rtol=1e-5, atol=1e-6,
                                   err_msg=jax.tree_util.keystr(pa))


def test_fsdp_composes_with_pp():
    """--fsdp with --pp: stage params sharded (pipe, ..., data) must produce
    the same step numerics as plain pipe sharding (GSPMD gathers at the
    pipeline's shard_map boundary; grads reduce-scatter back)."""
    from pytorch_distributed_tpu.parallel.fsdp import fsdp_specs

    rng = np.random.default_rng(2)
    tokens = rng.integers(0, VOCAB, size=(BATCH, SEQ)).astype(np.int32)
    mesh = build_mesh(MeshSpec(("data", "pipe"), (2, STAGES)),
                      jax.devices()[:2 * STAGES])
    model = PipelinedTransformerLM(
        vocab_size=VOCAB, d_model=D, n_heads=HEADS, n_layers=LAYERS,
        n_stages=STAGES, n_microbatches=2, mesh=mesh,
    )
    results = []
    with mesh:
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        base = pp_specs(params)
        zero3 = fsdp_specs(params, mesh, base_specs=base, min_size=64)
        assert zero3 != base, "fsdp_specs left the pp layout unchanged"
        for spec in (base, zero3):
            # Fresh copies: the train step donates its input state, and
            # shard_state may alias already-matching buffers.
            fresh = jax.tree_util.tree_map(jnp.array, params)
            state = shard_state(
                TrainState.create({"params": fresh}, sgd_init(fresh)),
                spec, mesh,
            )
            step = make_lm_train_step(model, mesh, spec, weight_decay=0.0)
            from jax.sharding import NamedSharding, PartitionSpec as P

            toks = jax.device_put(tokens,
                                  NamedSharding(mesh, P("data", None)))
            new_state, metrics = step(state, toks, jnp.float32(0.05))
            results.append((jax.device_get(new_state.params),
                            float(metrics["loss"])))
    (p_base, l_base), (p_z3, l_z3) = results
    assert l_base == pytest.approx(l_z3, rel=1e-5)
    for (pa, la), (pb, lb) in zip(
        jax.tree_util.tree_leaves_with_path(p_base),
        jax.tree_util.tree_leaves_with_path(p_z3),
    ):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   rtol=1e-5, atol=1e-6,
                                   err_msg=jax.tree_util.keystr(pa))


def test_lm_pretrain_1f1b_fsdp_runs_and_learns(capsys, tmp_path):
    from pytorch_distributed_tpu.recipes import lm_pretrain

    final = lm_pretrain.main([
        "--vocab", "32", "--d-model", "32", "--n-heads", "2",
        "--n-layers", "4", "--seq-len", "32", "-b", "8",
        "--steps", "15", "--lr", "0.05", "-p", "4",
        "--dataset-length", "8", "--precision", "fp32",
        "--pp", "4", "--schedule", "1f1b", "--fsdp", "--no-eval",
        "--checkpoint-dir", str(tmp_path),
    ])
    out = capsys.readouterr().out
    assert "Final loss" in out
    first = float(out.split("Loss ")[1].split(" ")[0])
    assert final < first
    assert (tmp_path / "checkpoint.msgpack").exists()


def test_1f1b_stash_is_m_independent():
    """The compiled 1F1B program's stash buffer is (2(P-1)+1)·mb stage
    inputs regardless of M — check via the jaxpr's scan carry shapes."""
    from pytorch_distributed_tpu.parallel.pp_1f1b import (
        pipeline_1f1b_loss_and_grads,
    )

    mesh = build_mesh(MeshSpec(("pipe",), (4,)), jax.devices()[:4])
    d = 8

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"])

    def head_fn(hp, y, tok):
        return jnp.mean(y.astype(jnp.float32) ** 2), jnp.float32(0.0)

    for M in (4, 16):
        B = M  # mb = 1
        x = jnp.ones((B, 4, d), jnp.float32)
        tok = jnp.zeros((B, 4), jnp.int32)
        params = {"w": jnp.ones((4, d, d), jnp.float32)}
        jaxpr = jax.make_jaxpr(
            lambda p, xx, tt: pipeline_1f1b_loss_and_grads(
                stage_fn, head_fn, p, {}, xx, tt, M, mesh,
            )[0]
        )(params, x, tok)
        # The stash appears in the scan carry as [S, mb, 4, d] with
        # S = 2*(4-1)+1 = 7 — never [M, ...].
        text = str(jaxpr)
        assert "7,1,4,8" in text.replace(" ", ""), text[:2000]
