"""TP inside pipeline stages: shard_map Megatron stage vs replicated oracle,
and the full dp×pipe×model pipelined LM."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
from pytorch_distributed_tpu.parallel.tp_stage import (
    init_stage_params,
    stage_param_specs,
    tp_stage_apply,
)

C, HEADS, BLOCKS = 32, 4, 2


def test_tp2_stage_matches_replicated_oracle():
    """Sharded stage (psums over 'model') ≡ the same math replicated."""
    params = init_stage_params(jax.random.PRNGKey(0), C, BLOCKS)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 8, C)).astype(np.float32))

    want = tp_stage_apply(params, x, HEADS, model_axis=None)

    mesh = build_mesh(MeshSpec(("model",), (2,)), jax.devices()[:2])
    # strip the leading pipe axis from the spec tree (single stage here)
    specs = jax.tree_util.tree_map(
        lambda s: P(*s[1:]), stage_param_specs(BLOCKS, "pipe", "model"),
        is_leaf=lambda s: isinstance(s, P),
    )["blocks"]
    got = jax.shard_map(
        lambda p, xb: tp_stage_apply(p, xb, HEADS, model_axis="model"),
        mesh=mesh,
        in_specs=({"blocks": specs}, P()),
        out_specs=P(),
        check_vma=False,
    )(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_pipelined_tp_lm_matches_tp1():
    """dp×pipe×model pipelined LM forward ≡ dp×pipe (tp=1) with the same
    tp_stage params."""
    from pytorch_distributed_tpu.models.pipeline_lm import (
        PipelinedTransformerLM,
    )

    mesh_tp = build_mesh(MeshSpec(("data", "pipe", "model"), (2, 2, 2)),
                         jax.devices()[:8])
    model_tp = PipelinedTransformerLM(
        vocab_size=64, d_model=C, n_heads=HEADS, n_layers=4, n_stages=2,
        n_microbatches=2, mesh=mesh_tp, tp_size=2,
    )
    rng = np.random.default_rng(1)
    tokens = jnp.asarray(rng.integers(0, 64, size=(4, 16)).astype(np.int32))
    with mesh_tp:
        variables = model_tp.init(jax.random.PRNGKey(0), tokens)
        got = model_tp.apply(variables, tokens)

    # tp=1 oracle over a data×pipe mesh but using the SAME tp_stage math:
    # apply each stage sequentially with the full params.
    from pytorch_distributed_tpu.parallel.tp_stage import tp_stage_apply

    p = variables["params"]
    x = model_tp._embed.apply({"params": p["embed"]}, tokens)
    for s in range(2):
        sp = jax.tree_util.tree_map(lambda a: a[s], p["stages"])
        x = tp_stage_apply(sp, x, HEADS, model_axis=None)
    x = model_tp._ln_f.apply({"params": p["ln_f"]}, x.astype(jnp.float32))
    want = model_tp._embed.apply(
        {"params": p["embed"]}, x, method=__import__("flax").linen.Embed.attend)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_pipelined_tp_lm_trains():
    """Full train step + eval through LMTrainer over data×pipe×model."""
    from pytorch_distributed_tpu.models.pipeline_lm import (
        PipelinedTransformerLM,
        pp_specs,
    )
    from pytorch_distributed_tpu.train.lm import LMTrainer, SyntheticTokenDataset

    mesh = build_mesh(MeshSpec(("data", "pipe", "model"), (2, 2, 2)),
                      jax.devices()[:8])
    model = PipelinedTransformerLM(
        vocab_size=32, d_model=C, n_heads=HEADS, n_layers=2, n_stages=2,
        n_microbatches=2, mesh=mesh, tp_size=2,
    )
    tokens0 = jnp.zeros((2, 16), jnp.int32)
    specs = pp_specs(model.init(jax.random.PRNGKey(0), tokens0)["params"],
                     model_axis="model")
    ds = SyntheticTokenDataset(8, 16, 32, seed=0)
    with mesh:
        t = LMTrainer(model, mesh, ds, batch_size=8, lr=0.05,
                      param_specs=specs, eval_dataset=ds, eval_batches=1)
        loss = t.fit(6, print_freq=3)
    assert np.isfinite(loss)


def test_lm_pretrain_pp_tp_runs_and_learns(capsys, tmp_path):
    from pytorch_distributed_tpu.recipes import lm_pretrain

    final = lm_pretrain.main([
        "--vocab", "32", "--d-model", "32", "--n-heads", "2",
        "--n-layers", "2", "--seq-len", "32", "-b", "8",
        "--steps", "15", "--lr", "0.05", "-p", "4",
        "--dataset-length", "8", "--precision", "fp32",
        "--pp", "2", "--tp", "2", "--no-eval",
        "--checkpoint-dir", str(tmp_path),
    ])
    out = capsys.readouterr().out
    assert "Final loss" in out
    first = float(out.split("Loss ")[1].split(" ")[0])
    assert final < first  # learns through the dp x pipe x model mesh
    assert (tmp_path / "checkpoint.msgpack").exists()


def test_pipelined_sp_lm_matches_sp1():
    """Ring SP inside pipeline stages: data×pipe×seq forward ≡ the
    replicated stagewise oracle with the same params."""
    from pytorch_distributed_tpu.models.pipeline_lm import (
        PipelinedTransformerLM,
    )
    from pytorch_distributed_tpu.parallel.tp_stage import tp_stage_apply

    mesh = build_mesh(MeshSpec(("data", "pipe", "seq"), (2, 2, 2)),
                      jax.devices()[:8])
    model = PipelinedTransformerLM(
        vocab_size=64, d_model=C, n_heads=HEADS, n_layers=2, n_stages=2,
        n_microbatches=2, mesh=mesh, sp_size=2,
    )
    rng = np.random.default_rng(2)
    tokens = jnp.asarray(rng.integers(0, 64, size=(4, 16)).astype(np.int32))
    with mesh:
        variables = model.init(jax.random.PRNGKey(0), tokens)
        got = model.apply(variables, tokens)

    p = variables["params"]
    x = model._embed.apply({"params": p["embed"]}, tokens)
    for s in range(2):
        sp = jax.tree_util.tree_map(lambda a: a[s], p["stages"])
        x = tp_stage_apply(sp, x, HEADS, model_axis=None, seq_axis=None)
    x = model._ln_f.apply({"params": p["ln_f"]}, x.astype(jnp.float32))
    want = model._embed.apply(
        {"params": p["embed"]}, x, method=__import__("flax").linen.Embed.attend)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=3e-4, atol=3e-4)


def test_quad_mesh_dp_pp_sp_tp_trains():
    """ALL FOUR axes in one mesh: data×pipe×seq×model (1×2×2×2) through the
    full LMTrainer train step + eval."""
    from pytorch_distributed_tpu.models.pipeline_lm import (
        PipelinedTransformerLM,
        pp_specs,
    )
    from pytorch_distributed_tpu.train.lm import LMTrainer, SyntheticTokenDataset

    mesh = build_mesh(
        MeshSpec(("data", "pipe", "seq", "model"), (1, 2, 2, 2)),
        jax.devices()[:8])
    model = PipelinedTransformerLM(
        vocab_size=32, d_model=C, n_heads=HEADS, n_layers=2, n_stages=2,
        n_microbatches=2, mesh=mesh, tp_size=2, sp_size=2,
    )
    tokens0 = jnp.zeros((2, 16), jnp.int32)
    specs = pp_specs(model.init(jax.random.PRNGKey(0), tokens0)["params"],
                     model_axis="model")
    # dataset-length == batch: same memorizable batch every step, so a few
    # steps must reduce the loss — exercising the backward through ring
    # attention nested in the pipeline scan, not just finiteness.
    ds = SyntheticTokenDataset(4, 16, 32, seed=0)
    with mesh:
        t = LMTrainer(model, mesh, ds, batch_size=4, lr=0.05,
                      param_specs=specs, eval_dataset=ds, eval_batches=1)
        first = None
        import io
        from contextlib import redirect_stdout

        buf = io.StringIO()
        with redirect_stdout(buf):
            loss = t.fit(12, print_freq=4)
        first = float(buf.getvalue().split("Loss ")[1].split(" ")[0])
    assert np.isfinite(loss)
    assert loss < first  # it learns through the quad mesh
