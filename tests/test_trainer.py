"""End-to-end slice: the minimum path of SURVEY.md §7.3 — synthetic data →
resnet18 → jitted DP train step over 8 simulated devices → meters → validate →
checkpoint → resume."""

import jax
import numpy as np
import pytest

from pytorch_distributed_tpu.train.config import Config, parse_config
from pytorch_distributed_tpu.train.trainer import Trainer


def _cfg(tmp_path, **kw):
    base = dict(
        arch="resnet18",
        batch_size=16,
        epochs=1,
        lr=0.1,
        print_freq=2,
        synthetic=True,
        synthetic_length=48,
        image_size=32,
        num_classes=8,
        seed=0,
        checkpoint_dir=str(tmp_path),
        workers=2,
    )
    base.update(kw)
    return Config(**base)


def test_fit_one_epoch_trains_and_checkpoints(tmp_path, capsys):
    # 2 classes ⇒ val acc ~50% ⇒ first epoch beats best_acc1=0, so the
    # reference's strict `acc1 > best` (distributed.py:215) triggers is_best.
    t = Trainer(_cfg(tmp_path, num_classes=2))
    p0 = jax.tree_util.tree_leaves(t.state.params)[0].copy()
    best = t.fit()
    out = capsys.readouterr().out
    assert "Epoch: [0]" in out
    assert "* Acc@1" in out
    assert (tmp_path / "checkpoint.msgpack").exists()
    assert (tmp_path / "model_best.msgpack").exists()  # first epoch is best
    p1 = jax.tree_util.tree_leaves(t.state.params)[0]
    assert not np.array_equal(np.asarray(p0), np.asarray(p1)), "params must move"
    assert 0.0 <= best <= 100.0


def test_resume_continues_from_checkpoint(tmp_path, capsys):
    t = Trainer(_cfg(tmp_path))
    t.fit()
    step_after = int(t.state.step)

    cfg2 = _cfg(tmp_path, resume=str(tmp_path / "checkpoint.msgpack"), epochs=2)
    t2 = Trainer(cfg2)
    assert cfg2.start_epoch == 1  # epoch 0 was saved ⇒ resume at 1
    assert int(t2.state.step) == step_after
    out = capsys.readouterr().out
    assert "resumed resnet18" in out
    t2.fit()
    assert int(t2.state.step) == 2 * step_after


def test_evaluate_flag_runs_validation_only(tmp_path, capsys):
    t = Trainer(_cfg(tmp_path, evaluate=True))
    s0 = int(t.state.step)
    t.fit()
    out = capsys.readouterr().out
    assert "* Acc@1" in out
    assert "Epoch: [0]" not in out
    assert int(t.state.step) == s0
    assert not (tmp_path / "checkpoint.msgpack").exists()


def test_bf16_precision_trains(tmp_path):
    t = Trainer(_cfg(tmp_path, precision="bf16"))
    t.train_loader.set_epoch(0)
    batch = next(iter(t.feeder(iter(t.train_loader))))
    import jax.numpy as jnp

    state, metrics = t.train_step(t.state, batch, jnp.float32(0.1))
    assert np.isfinite(float(metrics["loss"]))
    # master params stay f32 under the bf16 compute policy
    assert jax.tree_util.tree_leaves(state.params)[0].dtype == jnp.float32


def test_parse_config_reference_flag_surface():
    cfg = parse_config(
        ["-a", "resnet50", "-b", "256", "--lr", "0.4", "--wd", "1e-4",
         "-p", "5", "-e", "--seed", "42", "-j", "8"]
    )
    assert cfg.arch == "resnet50"
    assert cfg.batch_size == 256
    assert cfg.lr == 0.4
    assert cfg.print_freq == 5
    assert cfg.evaluate is True
    assert cfg.seed == 42
    assert cfg.workers == 8
