"""C++ data-plane library vs numpy semantics."""

import numpy as np
import pytest

from pytorch_distributed_tpu.data.native import native_available, normalize_batch
from pytorch_distributed_tpu.data.native import binding

MEAN = np.array([0.485, 0.456, 0.406], np.float32)
STD = np.array([0.229, 0.224, 0.225], np.float32)


def _numpy_oracle(u8, flip=None):
    x = u8.astype(np.float32) / 255.0
    if flip is not None:
        idx = np.nonzero(flip)[0]
        x[idx] = x[idx, :, ::-1, :]
    return (x - MEAN) / STD


def test_native_builds_and_loads():
    assert native_available(), "g++ is baked into the image; build must succeed"


def test_normalize_matches_numpy():
    rng = np.random.default_rng(0)
    u8 = rng.integers(0, 256, size=(4, 16, 24, 3)).astype(np.uint8)
    got = normalize_batch(u8, MEAN, STD)
    np.testing.assert_allclose(got, _numpy_oracle(u8), rtol=1e-6, atol=1e-6)


def test_normalize_with_flip():
    rng = np.random.default_rng(1)
    u8 = rng.integers(0, 256, size=(5, 8, 10, 3)).astype(np.uint8)
    flip = np.array([1, 0, 1, 0, 1], np.uint8)
    got = normalize_batch(u8, MEAN, STD, flip=flip)
    np.testing.assert_allclose(got, _numpy_oracle(u8, flip), rtol=1e-6, atol=1e-6)


def test_multithreaded_matches_single():
    rng = np.random.default_rng(2)
    u8 = rng.integers(0, 256, size=(16, 32, 32, 3)).astype(np.uint8)
    flip = (rng.random(16) < 0.5).astype(np.uint8)
    a = normalize_batch(u8, MEAN, STD, flip=flip, n_threads=1)
    b = normalize_batch(u8, MEAN, STD, flip=flip, n_threads=4)
    np.testing.assert_array_equal(a, b)


def test_fallback_path_same_semantics(monkeypatch):
    rng = np.random.default_rng(3)
    u8 = rng.integers(0, 256, size=(3, 8, 8, 3)).astype(np.uint8)
    flip = np.array([0, 1, 0], np.uint8)
    fast = normalize_batch(u8, MEAN, STD, flip=flip)
    monkeypatch.setattr(binding, "_load", lambda: None)
    slow = normalize_batch(u8, MEAN, STD, flip=flip)
    np.testing.assert_allclose(fast, slow, rtol=1e-6, atol=1e-6)


# ------------------------------------------------------- native JPEG decode

def _jpeg_bytes(arr):
    import io

    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="JPEG", quality=95)
    return buf.getvalue()


def test_decode_eval_matches_pil_resize_centercrop():
    """Eval semantics: short-side resize + center crop ≈ the PIL u8 stack
    (different bilinear kernels ⇒ tolerance, not equality)."""
    from pytorch_distributed_tpu.data.native import (
        decode_crop_resize_batch,
        jpeg_native_available,
    )

    if not jpeg_native_available():
        import pytest

        pytest.skip("libjpeg not available")
    from PIL import Image

    rng = np.random.default_rng(0)
    # smooth image: decode/resample differences stay small
    base = rng.normal(0.5, 0.2, size=(13, 17))
    src = np.clip(
        np.kron(base, np.ones((24, 24)))[None].repeat(3, 0).transpose(1, 2, 0),
        0, 1)
    src = (src * 255).astype(np.uint8)[:280, :360]
    blob = _jpeg_bytes(src)
    out = decode_crop_resize_batch([blob], 224, params=None)[0]
    assert out.shape == (224, 224, 3) and out.dtype == np.uint8

    with Image.open(__import__("io").BytesIO(blob)) as im:
        im = im.convert("RGB")
        w, h = im.size
        scale = 256 / min(w, h)
        im = im.resize((round(w * scale), round(h * scale)), Image.BILINEAR)
        left = (im.width - 224) // 2
        top = (im.height - 224) // 2
        ref = np.asarray(im.crop((left, top, left + 224, top + 224)))
    diff = np.abs(out.astype(np.float32) - ref.astype(np.float32))
    assert diff.mean() < 4.0, diff.mean()


def test_decode_train_params_deterministic_and_full_area():
    from pytorch_distributed_tpu.data.native import (
        decode_crop_resize_batch,
        jpeg_native_available,
    )

    if not jpeg_native_available():
        import pytest

        pytest.skip("libjpeg not available")
    rng = np.random.default_rng(1)
    src = rng.integers(0, 256, size=(160, 200, 3), dtype=np.uint8)
    blob = _jpeg_bytes(src)
    # area_frac=1, ratio=0 -> full-ish crop; u,v irrelevant
    params = np.array([[1.0, 0.0, 0.3, 0.7]], np.float32)
    a = decode_crop_resize_batch([blob], 96, params=params)
    b = decode_crop_resize_batch([blob], 96, params=params)
    np.testing.assert_array_equal(a, b)
    # different draw -> different crop
    params2 = np.array([[0.2, 0.1, 0.1, 0.1]], np.float32)
    c = decode_crop_resize_batch([blob], 96, params=params2)
    assert np.abs(a.astype(int) - c.astype(int)).mean() > 1.0


def test_decode_corrupt_blob_zeroed():
    from pytorch_distributed_tpu.data.native import (
        decode_crop_resize_batch,
        jpeg_native_available,
    )

    if not jpeg_native_available():
        import pytest

        pytest.skip("libjpeg not available")
    rng = np.random.default_rng(2)
    good = _jpeg_bytes(rng.integers(0, 256, size=(64, 64, 3), dtype=np.uint8))
    out = decode_crop_resize_batch([good, b"not a jpeg"], 32, params=None)
    assert out[0].any()
    assert not out[1].any()


def test_native_loader_end_to_end(tmp_path):
    """ImageFolder(native_decode) through DataLoader: u8 batches, flips,
    padding mask — the --wire native path."""
    from pytorch_distributed_tpu.data.native import jpeg_native_available

    if not jpeg_native_available():
        import pytest

        pytest.skip("libjpeg not available")
    from PIL import Image

    from pytorch_distributed_tpu.data import DataLoader, ImageFolder

    rng = np.random.default_rng(3)
    for c in range(2):
        d = tmp_path / "train" / f"c{c}"
        d.mkdir(parents=True)
        for i in range(5):
            arr = rng.integers(0, 256, size=(90, 110, 3), dtype=np.uint8)
            Image.fromarray(arr).save(d / f"{i}.jpg")
    ds = ImageFolder(str(tmp_path / "train"), native_decode=True,
                     image_size=64, native_augment=True)
    loader = DataLoader(ds, 4, num_workers=2, seed=0,
                        batch_mode="u8_wire", random_flip=True)
    batches = list(loader)
    assert len(batches) == 3  # 10 samples / batch 4, padded tail
    for b in batches:
        assert b["images"].dtype == np.uint8
        assert b["images"].shape == (4, 64, 64, 3)
    assert batches[-1]["weights"].sum() == 2.0  # 10 = 4+4+2
    # eval-mode dataset goes through the no-params path
    ds_eval = ImageFolder(str(tmp_path / "train"), native_decode=True,
                          image_size=64, native_augment=False)
    b0 = next(iter(DataLoader(ds_eval, 4, num_workers=2,
                              batch_mode="u8_host")))
    assert b0["images"].dtype == np.float32  # u8_host normalizes on host


def test_native_loader_masks_corrupt_files(tmp_path):
    from pytorch_distributed_tpu.data.native import jpeg_native_available

    if not jpeg_native_available():
        import pytest

        pytest.skip("libjpeg not available")
    from PIL import Image

    from pytorch_distributed_tpu.data import DataLoader, ImageFolder

    rng = np.random.default_rng(5)
    d = tmp_path / "train" / "c0"
    d.mkdir(parents=True)
    for i in range(3):
        arr = rng.integers(0, 256, size=(70, 70, 3), dtype=np.uint8)
        Image.fromarray(arr).save(d / f"{i}.jpg")
    (d / "3.jpg").write_bytes(b"garbage not jpeg")
    ds = ImageFolder(str(tmp_path / "train"), native_decode=True,
                     image_size=32, native_augment=False)
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        batches = list(DataLoader(ds, 4, num_workers=2,
                                  batch_mode="u8_wire"))
    assert len(batches) == 1
    # 4 files, 1 corrupt -> 3 live weights
    assert batches[0]["weights"].sum() == 3.0
