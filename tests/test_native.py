"""C++ data-plane library vs numpy semantics."""

import numpy as np
import pytest

from pytorch_distributed_tpu.data.native import native_available, normalize_batch
from pytorch_distributed_tpu.data.native import binding

MEAN = np.array([0.485, 0.456, 0.406], np.float32)
STD = np.array([0.229, 0.224, 0.225], np.float32)


def _numpy_oracle(u8, flip=None):
    x = u8.astype(np.float32) / 255.0
    if flip is not None:
        idx = np.nonzero(flip)[0]
        x[idx] = x[idx, :, ::-1, :]
    return (x - MEAN) / STD


def test_native_builds_and_loads():
    assert native_available(), "g++ is baked into the image; build must succeed"


def test_normalize_matches_numpy():
    rng = np.random.default_rng(0)
    u8 = rng.integers(0, 256, size=(4, 16, 24, 3)).astype(np.uint8)
    got = normalize_batch(u8, MEAN, STD)
    np.testing.assert_allclose(got, _numpy_oracle(u8), rtol=1e-6, atol=1e-6)


def test_normalize_with_flip():
    rng = np.random.default_rng(1)
    u8 = rng.integers(0, 256, size=(5, 8, 10, 3)).astype(np.uint8)
    flip = np.array([1, 0, 1, 0, 1], np.uint8)
    got = normalize_batch(u8, MEAN, STD, flip=flip)
    np.testing.assert_allclose(got, _numpy_oracle(u8, flip), rtol=1e-6, atol=1e-6)


def test_multithreaded_matches_single():
    rng = np.random.default_rng(2)
    u8 = rng.integers(0, 256, size=(16, 32, 32, 3)).astype(np.uint8)
    flip = (rng.random(16) < 0.5).astype(np.uint8)
    a = normalize_batch(u8, MEAN, STD, flip=flip, n_threads=1)
    b = normalize_batch(u8, MEAN, STD, flip=flip, n_threads=4)
    np.testing.assert_array_equal(a, b)


def test_fallback_path_same_semantics(monkeypatch):
    rng = np.random.default_rng(3)
    u8 = rng.integers(0, 256, size=(3, 8, 8, 3)).astype(np.uint8)
    flip = np.array([0, 1, 0], np.uint8)
    fast = normalize_batch(u8, MEAN, STD, flip=flip)
    monkeypatch.setattr(binding, "_load", lambda: None)
    slow = normalize_batch(u8, MEAN, STD, flip=flip)
    np.testing.assert_allclose(fast, slow, rtol=1e-6, atol=1e-6)
