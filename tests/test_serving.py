"""Continuous-batching serving engine (ISSUE 15): paged KV pool units,
scheduler determinism, bit-exact parity with models/generate.py, and the
zero-recompile soak.

The parity tests are the load-bearing ones: the paged engine must emit
*bit-identical* greedy tokens to the dense KV-cache reference for every
request in a mixed-length trace — batched, chunk-prefilled, behind
admission/preemption, and under speculative decoding.  Everything the
engine does (block tables, null-block routing, recompute-on-preempt) is
invisible or it's wrong.

All engine tests run on a fake clock (time_fn/sleep_fn injection), so
they are deterministic and never actually sleep.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.models.generate import greedy_generate
from pytorch_distributed_tpu.serving.engine import (
    ServingEngine,
    init_lm_params,
)
from pytorch_distributed_tpu.serving.kvpool import (
    BlockPool,
    apply_permutation,
    init_pools,
    lookup_blocks,
    paged_gather,
)
from pytorch_distributed_tpu.serving.loadgen import (
    LoadConfig,
    generate_load,
)
from pytorch_distributed_tpu.serving.scheduler import Request, Scheduler

CFG = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=2)
BS = 8  # KV block size shared by every engine test (reuses compiles)


def _params(seed=0):
    return init_lm_params(block_size=BS, seed=seed, **CFG)


def _fake_clock():
    t = [0.0]
    return (lambda: t[0]), (lambda s: t.__setitem__(0, t[0] + max(s, 1e-3)))


def _engine(params, **kw):
    time_fn, sleep_fn = _fake_clock()
    defaults = dict(max_batch=4, kv_blocks=17, block_size=BS,
                    blocks_per_seq=8, chunk_size=8, max_new_tokens=64,
                    time_fn=time_fn, sleep_fn=sleep_fn, seed=0, **CFG)
    defaults.update(kw)
    return ServingEngine(params, **defaults)


def _mk_load(seed, n, pmin=2, pmax=10, nmin=2, nmax=12):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        p = rng.integers(0, CFG["vocab_size"],
                         size=int(rng.integers(pmin, pmax + 1))).tolist()
        out.append((0.0, Request(rid=i, prompt=p,
                                 max_new_tokens=int(
                                     rng.integers(nmin, nmax + 1)))))
    return out


def _reference(params, load):
    """Dense-cache greedy reference, one request at a time."""
    want = {}
    for _, req in load:
        prompt = jnp.asarray([list(req.prompt)], jnp.int32)
        got = greedy_generate(params, prompt, req.max_new_tokens,
                              flash_prefill=False, **CFG)
        want[req.rid] = np.asarray(got)[0].tolist()
    return want


# --------------------------------------------------------------- kvpool

def test_blockpool_alloc_free_accounting():
    pool = BlockPool(n_blocks=9, block_size=4, blocks_per_seq=4)
    assert pool.capacity_blocks == 8  # block 0 is the reserved null sink
    assert pool.capacity_tokens == 16
    assert pool.blocks_needed(1) == 1 and pool.blocks_needed(4) == 1
    assert pool.blocks_needed(5) == 2
    assert pool.ensure(7, 6)  # 2 blocks
    assert pool.used_blocks == 2 and pool.free_blocks == 6
    assert pool.ensure(7, 8)  # still 2 blocks: grow within allocation
    assert pool.used_blocks == 2
    assert pool.ensure(7, 9)  # third block
    assert pool.used_blocks == 3
    assert 0 not in pool.blocks_of(7)
    assert pool.free(7) == 3
    assert pool.used_blocks == 0 and pool.free_blocks == 8
    assert pool.occupancy_pct() == 0.0


def test_blockpool_exhaustion_counts_failures():
    pool = BlockPool(n_blocks=5, block_size=4, blocks_per_seq=4)
    assert pool.ensure(1, 16)  # all 4 usable blocks
    assert not pool.can_alloc(1)
    assert not pool.ensure(2, 1)
    assert pool.alloc_failures == 1
    with pytest.raises(ValueError):
        pool.ensure(3, 17)  # beyond per-seq capacity: admission bug
    pool.free(1)
    assert pool.ensure(2, 1)


def test_blockpool_defrag_preserves_gathered_kv():
    """Free a middle sequence, defrag, permute the device pool: gathers
    through the rewritten tables must be bit-identical."""
    pool = BlockPool(n_blocks=8, block_size=4, blocks_per_seq=3)
    for sid, toks in ((0, 8), (1, 8), (2, 8)):
        assert pool.ensure(sid, toks)
    pool.free(1)
    assert pool.fragmentation_pct() > 0.0

    pk, _ = init_pools(1, 8, 4, n_heads=2, head_dim=4)
    # stamp every block with its own id so moves are detectable
    pk = pk.at[:].set(jnp.arange(8, dtype=jnp.float32)[None, :, None,
                                                       None, None])
    before = {sid: np.asarray(paged_gather(pk[0], jnp.asarray(
        pool.table([sid])))) for sid in (0, 2)}

    perm = pool.defrag()
    assert pool.defrags == 1
    assert pool.fragmentation_pct() == 0.0
    assert sorted(pool.blocks_of(0) + pool.blocks_of(2)) == [1, 2, 3, 4]
    pk2 = apply_permutation(pk, jnp.asarray(perm))
    for sid in (0, 2):
        after = np.asarray(paged_gather(pk2[0], jnp.asarray(
            pool.table([sid]))))
        np.testing.assert_array_equal(after, before[sid])
    # nothing to move: identity perm, counter untouched
    perm2 = pool.defrag()
    np.testing.assert_array_equal(perm2, np.arange(8))
    assert pool.defrags == 1


def test_blockpool_null_routing():
    pool = BlockPool(n_blocks=8, block_size=4, blocks_per_seq=2)
    assert pool.ensure(5, 3)
    tab = pool.table([5, None])
    assert tab.shape == (2, 2) and tab.dtype == np.int32
    assert (tab[1] == 0).all()  # empty lane reads the null block
    # out-of-window positions route to block 0, never past the table
    blk = np.asarray(lookup_blocks(jnp.asarray(tab),
                                   jnp.asarray([[9], [0]], jnp.int32), 4))
    assert blk[0, 0] == 0


# ------------------------------------------------------------ scheduler

def test_scheduler_fcfs_admission_is_submit_order():
    s = Scheduler(max_batch=2)
    reqs = [Request(rid=i, prompt=[0], max_new_tokens=1) for i in range(4)]
    for r in reqs:
        s.submit(r)
    placed = s.admit(lambda r: True)
    assert [(i, r.rid) for i, r in placed] == [(0, 0), (1, 1)]
    assert s.queue_depth == 2
    s.finish(0)
    assert [(i, r.rid) for i, r in s.admit(lambda r: True)] == [(0, 2)]


def test_scheduler_priority_policy_and_hol_blocking():
    s = Scheduler(max_batch=1, policy="priority")
    s.submit(Request(rid=0, prompt=[0], max_new_tokens=1, priority=0))
    s.submit(Request(rid=1, prompt=[0], max_new_tokens=1, priority=5))
    placed = s.admit(lambda r: True)
    assert placed[0][1].rid == 1  # higher priority jumps the queue
    s.finish(0)
    # head-of-line blocking: a rejected head blocks everything behind it
    s.submit(Request(rid=2, prompt=[0], max_new_tokens=1, priority=9))
    assert s.admit(lambda r: False) == []
    assert s.queue_depth == 2


def test_scheduler_preempt_requeues_at_original_position():
    s = Scheduler(max_batch=2)
    for i in range(3):
        s.submit(Request(rid=i, prompt=[0], max_new_tokens=4))
    s.admit(lambda r: True)  # rids 0, 1 active; rid 2 queued
    victim = s.slots[1]
    victim.generated = [7, 7]
    s.preempt(1)
    assert victim.generated == [] and victim.preemptions == 1
    # the preempted rid 1 re-enters AHEAD of the later-submitted rid 2
    placed = s.admit(lambda r: True)
    assert [r.rid for _, r in placed] == [1]
    assert s.preemptions == 1


def test_scheduler_duplicate_rid_requeue_never_compares_requests():
    """A requeue whose key collides with a queued duplicate rid must
    resolve on the tiebreaker, not by comparing Request objects."""
    s = Scheduler(max_batch=1)
    a = Request(rid=0, prompt=[0], max_new_tokens=4)
    s.submit(a)
    s.admit(lambda r: True)
    b = Request(rid=0, prompt=[1], max_new_tokens=4)
    s.submit(b)   # same rid while a is active: requeue key will collide
    s.preempt(0)  # pre-fix: TypeError inside heapq comparing a vs b
    assert s.queue_depth == 2
    placed = s.admit(lambda r: True)
    assert placed[0][1] is b  # equal keys pop FIFO: b entered first


def test_scheduler_victim_is_lowest_priority_then_youngest():
    s = Scheduler(max_batch=3, policy="priority")
    s.submit(Request(rid=0, prompt=[0], max_new_tokens=1, priority=2))
    s.submit(Request(rid=1, prompt=[0], max_new_tokens=1, priority=1))
    s.submit(Request(rid=2, prompt=[0], max_new_tokens=1, priority=1))
    s.admit(lambda r: True)
    # both rid 1 and 2 have the low priority; rid 2 was admitted later
    slot = s.pick_victim()
    assert s.slots[slot].rid == 2
    assert s.pick_victim(protect=(slot,)) != slot


# -------------------------------------------------- engine: exact parity

def test_continuous_engine_bit_exact_vs_dense_reference():
    """Mixed-length trace through admission, chunked prefill, and paged
    decode must reproduce the dense-cache greedy tokens bit-for-bit."""
    params = _params()
    load = _mk_load(seed=3, n=10)
    want = _reference(params, load)
    eng = _engine(params)
    summary = eng.run(load)
    assert summary["completed"] == 10
    got = {r.rid: list(r.generated) for r in eng.finished}
    assert got == want


def test_static_mode_same_tokens_fewer_assumptions():
    """mode="static" (the A/B baseline) is a scheduling change only: the
    emitted tokens must be identical to continuous mode."""
    params = _params()
    load_a = _mk_load(seed=4, n=8)
    load_b = _mk_load(seed=4, n=8)
    eng_a = _engine(params, mode="continuous")
    eng_a.run(load_a)
    eng_b = _engine(params, mode="static")
    s = eng_b.run(load_b)
    assert s["completed"] == 8
    assert ({r.rid: list(r.generated) for r in eng_a.finished}
            == {r.rid: list(r.generated) for r in eng_b.finished})


def test_preemption_recompute_parity():
    """A pool too small for the offered load must preempt-and-requeue —
    and, because recompute under greedy is deterministic, finish with
    exactly the tokens an unconstrained pool produces."""
    params = _params()

    def load():
        return [(0.0, Request(rid=i, prompt=[i + 1, i + 2, i + 3, i + 4],
                              max_new_tokens=20)) for i in range(4)]

    big = _engine(params, kv_blocks=33, blocks_per_seq=4)
    big.run(load())
    assert big.summary()["preemptions"] == 0

    tiny = _engine(params, kv_blocks=7, blocks_per_seq=4)
    s = tiny.run(load())
    assert s["completed"] == 4
    assert s["preemptions"] > 0
    assert ({r.rid: list(r.generated) for r in tiny.finished}
            == {r.rid: list(r.generated) for r in big.finished})


def test_admission_does_not_overcommit_pool():
    """Two queued requests that each fit the pool alone but not together
    must admit one after the other — the admit loop used to probe every
    candidate against the same unchanged free count, so the second
    prefill found its blocks already gone."""
    params = _params()

    def load():
        # 20-token prompts = 3 blocks each; the tiny pool has 4 usable
        return [(0.0, Request(rid=i, prompt=[i + 1] * 20,
                              max_new_tokens=6)) for i in range(2)]

    big = _engine(params, kv_blocks=33)
    big.run(load())
    tiny = _engine(params, kv_blocks=5, blocks_per_seq=4)
    s = tiny.run(load())
    assert s["completed"] == 2
    assert s["preemptions"] == 0 and s["alloc_failures"] == 0
    assert ({r.rid: list(r.generated) for r in tiny.finished}
            == {r.rid: list(r.generated) for r in big.finished})


def test_growth_cannot_evict_a_validated_lane():
    """A later lane's pool-growth preemption must never pick a victim
    already validated into this step's decode batch — the stale pair
    would decode through a zeroed table row into a requeued request
    whose generation was just reset."""
    params = _params()

    def drive(**kw):
        # low-priority r0 lands in slot 0 first; high-priority r1 joins
        # in slot 1, and its growth pressure would (pre-fix) evict the
        # already-validated slot 0 mid-step.  r1 then finishes fast and
        # frees the pool, so the garbage token the stale lane appended
        # survives into r0's recomputed output instead of being wiped by
        # another preemption.
        eng = _engine(params, policy="priority", max_batch=2,
                      blocks_per_seq=4, **kw)

        def stream(rid, tok, kind):
            # every emitted token must belong to a request that occupies
            # a slot RIGHT NOW — a stale evicted lane fails this no
            # matter what token value the zeroed table row produces
            assert any(r is not None and r.rid == rid
                       for r in eng.sched.slots), (rid, tok, kind)

        eng.stream = stream
        eng.submit(Request(rid=0, prompt=list(range(1, 9)),
                           max_new_tokens=4, priority=0))
        eng.step()
        eng.submit(Request(rid=1, prompt=list(range(9, 25)),
                           max_new_tokens=2, priority=5))
        for _ in range(30):
            if eng.sched.completed == 2:
                break
            eng.step()
        assert eng.sched.completed == 2
        return eng

    big = drive(kv_blocks=33)
    assert big.summary()["preemptions"] == 0
    tiny = drive(kv_blocks=5)  # 4 usable blocks: r1's growth exhausts
    assert tiny.summary()["preemptions"] > 0
    assert ({r.rid: list(r.generated) for r in tiny.finished}
            == {r.rid: list(r.generated) for r in big.finished})


def test_speculative_decode_bit_exact():
    """gamma=3 speculative decoding accepts/rejects against the target's
    own greedy argmax, so outputs must be identical to gamma=0."""
    params = _params()
    draft = init_lm_params(CFG["vocab_size"], 16, CFG["n_heads"], 1,
                           block_size=BS, seed=1)
    load_a = _mk_load(seed=5, n=6)
    load_b = _mk_load(seed=5, n=6)
    plain = _engine(params)
    plain.run(load_a)
    spec = _engine(params, gamma=3, draft_params=draft)
    s = spec.run(load_b)
    assert s["completed"] == 6
    assert ({r.rid: list(r.generated) for r in spec.finished}
            == {r.rid: list(r.generated) for r in plain.finished})
    # speculative rounds emit >= 1 token each, so never MORE iterations
    assert s["steps"] <= plain.summary()["steps"]


def test_int8_serving_smoke():
    from pytorch_distributed_tpu.models.quant import quantize_lm_params

    params = quantize_lm_params(_params())
    load = _mk_load(seed=6, n=4, nmax=6)
    eng = _engine(params, quant="int8")
    s = eng.run(load)
    assert s["completed"] == 4
    for r in eng.finished:
        assert len(r.generated) == r.max_new_tokens
        assert all(0 <= t < CFG["vocab_size"] for t in r.generated)


def test_streaming_callback_ordering():
    params = _params()
    load = _mk_load(seed=7, n=5)
    events = []
    eng = _engine(params, stream=lambda rid, tok, kind:
                  events.append((rid, tok, kind)))
    eng.run(load)
    per_rid = {}
    for rid, tok, kind in events:
        per_rid.setdefault(rid, []).append((tok, kind))
    for r in eng.finished:
        toks = per_rid[r.rid]
        # exactly one "first" per request, and it is the first event
        assert [k for _, k in toks] == (["first"]
                                        + ["token"] * (len(toks) - 1))
        assert [t for t, _ in toks] == list(r.generated)


# ----------------------------------------- engine: recompiles + metrics

def test_zero_recompile_soak_with_defrag():
    """Mixed-length churn (admissions, finishes, defrags) across a soak
    must never retrace the serving steps: the static-shape contract."""
    from pytorch_distributed_tpu.obs.watchdog import RecompileWatchdog

    params = _params()
    load = [(t, r) for t, r in generate_load(LoadConfig(
        n_requests=24, rate_rps=500.0, profile="mixed",
        vocab_size=CFG["vocab_size"], seed=8))]
    wd = RecompileWatchdog()
    wd.install()
    try:
        eng = _engine(params, watchdog=wd, defrag_threshold_pct=10.0)
        s = eng.run(load)
    finally:
        wd.uninstall()
    assert s["completed"] == 24
    assert s["defrags"] >= 1, "soak never exercised the defrag path"
    assert wd.anomalies == [], [a for a in wd.anomalies]


def test_engine_emits_slo_fields_and_events():
    from pytorch_distributed_tpu.obs.metrics import MetricsLogger

    params = _params()
    records = []
    obs = MetricsLogger(None, flush_every=1)
    obs.register(records.append)
    eng = _engine(params, obs=obs, kv_blocks=7, blocks_per_seq=4)
    eng.run([(0.0, Request(rid=i, prompt=[1, 2, 3, 4], max_new_tokens=20))
             for i in range(4)])
    obs.close()
    steps = [r for r in records if "ft_event" not in r
             and r.get("serving")]
    assert steps, "no serving step records logged"
    last = steps[-1]
    for field in ("queue_depth", "active_seqs", "kv_occupancy_pct",
                  "kv_frag_pct", "preemptions", "requests_completed",
                  "tokens_per_s", "ttft_p50_ms", "ttft_p99_ms"):
        assert field in last, field
    assert any(r.get("ft_event") == "serve_preempt" for r in records)


def test_ttft_and_kv_alert_rules():
    from pytorch_distributed_tpu.obs.alerts import AlertEngine, Rule

    booked = []
    ae = AlertEngine(
        [Rule("ttft_p99", "ttft", "page", {"max_ms": 100.0}),
         Rule("kv_occupancy", "kv", "warn", {"max_pct": 90.0})],
        emit=lambda **f: booked.append(f))
    ae.observe({"step": 1, "step_time": 0.01, "ttft_p99_ms": 50.0,
                "kv_occupancy_pct": 10.0})
    assert booked == []
    ae.observe({"step": 2, "step_time": 0.01, "ttft_p99_ms": 150.0,
                "kv_occupancy_pct": 95.0})
    assert {b["alert"] for b in booked} == {"ttft", "kv"}
    # latched: the same breach does not re-book
    ae.observe({"step": 3, "step_time": 0.01, "ttft_p99_ms": 150.0,
                "kv_occupancy_pct": 95.0})
    assert len(booked) == 2
    # recovery clears the latch; the next breach books again
    ae.observe({"step": 4, "step_time": 0.01, "ttft_p99_ms": 50.0,
                "kv_occupancy_pct": 10.0})
    ae.observe({"step": 5, "step_time": 0.01, "ttft_p99_ms": 150.0,
                "kv_occupancy_pct": 10.0})
    assert [b["alert"] for b in booked] == ["ttft", "kv", "ttft"]


def test_exporter_renders_serving_gauges():
    from pytorch_distributed_tpu.obs.export import (
        MetricsExporter,
        parse_prometheus,
    )

    ex = MetricsExporter(port=0)
    ex.update({"step": 3, "step_time": 0.01, "serving": 1.0,
               "ttft_p99_ms": 42.0, "itl_p50_ms": 2.0,
               "queue_depth": 5.0, "kv_occupancy_pct": 61.0,
               "preemptions": 2.0, "tokens_per_s": 512.0})
    samples = {(n, lab.get("quantile")): v
               for n, lab, v in parse_prometheus(ex.render())}
    assert samples[("ptd_serving_ttft_ms", "p99")] == 42.0
    assert samples[("ptd_serving_itl_ms", "p50")] == 2.0
    assert samples[("ptd_serving_queue_depth", None)] == 5.0
    assert samples[("ptd_serving_kv_occupancy_pct", None)] == 61.0
    assert samples[("ptd_serving_preemptions_total", None)] == 2.0
    assert samples[("ptd_serving_tokens_per_second", None)] == 512.0
    # serving fields must not double-render as generic ptd_metric rows
    generic = [lab.get("field") for n, lab, _ in
               parse_prometheus(ex.render()) if n == "ptd_metric"]
    assert "ttft_p99_ms" not in generic


def test_serving_recipes_registered_and_baselined():
    import json
    import os

    from pytorch_distributed_tpu.analysis import core

    assert "serve_prefill" in core.RECIPES
    assert "serve_decode" in core.RECIPES
    base = json.load(open(os.path.join(
        os.path.dirname(core.__file__), "baseline.json")))
    for name in ("serve_prefill", "serve_decode"):
        assert name in base, f"{name} missing from analysis/baseline.json"
        assert base[name]["peak_hbm_bytes"] > 0
    # serving recipes are single-host: no collectives on the wire
    assert base["serve_decode"]["total_bytes"] == 0


def test_engine_shares_compiled_steps_with_recipes():
    """The analysis recipes and the live engine must hit the same cached
    jitted callables — zero extra compiles for the registered steps."""
    from pytorch_distributed_tpu.serving.engine import _make_steps

    a = _make_steps(64, 32, 4, 2, BS, 0.0, 0, 1.0, "")
    b = _make_steps(64, 32, 4, 2, BS, 0.0, 0, 1.0, "")
    assert a is b
    assert a.decode is b.decode and a.prefill is b.prefill


def test_loadgen_deterministic_and_mixed():
    a = generate_load(LoadConfig(n_requests=16, seed=9))
    b = generate_load(LoadConfig(n_requests=16, seed=9))
    assert [(t, r.rid, list(r.prompt), r.max_new_tokens) for t, r in a] \
        == [(t, r.rid, list(r.prompt), r.max_new_tokens) for t, r in b]
    c = generate_load(LoadConfig(n_requests=16, seed=10))
    assert [r.max_new_tokens for _, r in a] \
        != [r.max_new_tokens for _, r in c]
    times = [t for t, _ in a]
    assert times == sorted(times) and times[0] >= 0.0
    lens = {r.max_new_tokens for _, r in
            generate_load(LoadConfig(n_requests=64, profile="mixed",
                                     seed=11))}
    cfg = LoadConfig()
    assert any(n <= cfg.short_max for n in lens)
    assert any(n >= cfg.long_min for n in lens)
