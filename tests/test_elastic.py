"""Elastic training fences (ISSUE 10 tentpole): re-mesh on rank loss/join.

Three layers, cheapest first:

- pure-python units for the membership/rescale/liveness pieces
  (ft/elastic.py) and the atomic epoch-stamped heartbeats they ride on;
- host-side numpy exactness fences for the state re-grid surgery: ZeRO-WUS
  momentum chunks and stacked error-feedback residuals must round-trip a
  world change bit-exactly (in the semantics that survive one);
- ONE in-process LM chaos drill on the simulated mesh — lose a rank
  mid-run, re-admit it later, and require the final loss to match the
  uninterrupted run within the pinned fence (RESULTS_elastic.json).
  ``--rescale none`` holds the GLOBAL batch constant, and a shrink rewinds
  to the last keeper snapshot, so the drill replays the identical batch
  sequence and the parity is tight.

The image-trainer drill (explicit collectives + int8 grad compress +
ZeRO-WUS all at once) and the cross-process coordinator drill through
scripts/elastic_agent.py are ``slow``: tier-1 wall-clock already brushes
the CI cap (ROADMAP "known debt"), and the elastic re-mesh machinery they
exercise is identical to the tier-1 LM drill's.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

import jax

from pytorch_distributed_tpu.ft import elastic as el
from pytorch_distributed_tpu.obs.heartbeat import (
    HeartbeatWriter,
    find_stragglers,
    read_heartbeats,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------------ units

def test_rescale_lr_rules():
    assert el.rescale_lr(0.1, 4, 2, "none") == 0.1
    assert el.rescale_lr(0.1, 4, 2, "linear") == pytest.approx(0.05)
    assert el.rescale_lr(0.1, 4, 8, "linear") == pytest.approx(0.2)
    assert el.rescale_lr(0.1, 4, 1, "sqrt") == pytest.approx(0.05)
    assert el.rescale_lr(0.1, 4, 4, "sqrt") == 0.1  # no-op on equal worlds
    with pytest.raises(ValueError, match="rescale rule"):
        el.rescale_lr(0.1, 4, 2, "bogus")


def test_rescale_batch_rules():
    # "none" holds the GLOBAL batch constant (per-rank batch grows on
    # shrink) — the rule that makes the drill's loss parity bit-tight.
    assert el.rescale_batch(12, 4, 3, "none") == 12
    # the LR rules hold the PER-RANK batch constant instead
    assert el.rescale_batch(12, 4, 3, "linear") == 9
    assert el.rescale_batch(12, 4, 8, "sqrt") == 24
    with pytest.raises(ValueError, match="not divisible"):
        el.rescale_batch(10, 4, 2, "linear")
    with pytest.raises(ValueError, match="rescale rule"):
        el.rescale_batch(12, 4, 2, "bogus")


def test_split_liveness_uses_monitor_classification():
    flagged = {
        1: "dead or hung: last beat 120s ago",
        2: "slow rank: ema 3.1x median",
        3: "slow rank + dead or hung",  # dead wins when both appear
    }
    dead, slow = el.split_liveness(flagged)
    assert dead == {1, 3}
    assert slow == {2}
    assert el.split_liveness({}) == (set(), set())


def test_membership_roundtrip_and_change_kind():
    m = el.Membership(3, (0, 1, 2))
    assert m.world == 3
    assert el.Membership.from_json(m.to_json()) == m
    shrink = el.MembershipChange(el.Membership(0, (0, 1, 2, 3)),
                                 el.Membership(1, (0, 1, 2)), "lost 3")
    grow = el.MembershipChange(el.Membership(1, (0, 1, 2)),
                               el.Membership(2, (0, 1, 2, 3)), "joined 3")
    assert (shrink.kind, grow.kind) == ("shrink", "grow")


def test_elastic_sim_protocol():
    sim = el.ElasticSim(world=4, min_ranks=3)
    assert sim.poll(0) is None                       # steady state: no-op
    sim.force_lose(3, reason="drill")
    chg = sim.poll(1)
    assert (chg.kind, chg.old.world, chg.new.world) == ("shrink", 4, 3)
    assert chg.new.epoch == 1 and "drill" in chg.reason
    sim.force_lose(2)                                # would go below floor
    assert sim.poll(2) is None
    assert sim.refused and sim.refused[0][0] == 2
    sim.force_join(3)
    chg = sim.poll(3)
    assert (chg.kind, chg.new.world, chg.new.epoch) == ("grow", 4, 2)
    assert [c.kind for c in sim.history] == ["shrink", "grow"]
    with pytest.raises(ValueError, match="min_ranks"):
        el.ElasticSim(world=2, min_ranks=3)


def test_heartbeat_atomic_write_and_epoch_fence(tmp_path):
    hb = str(tmp_path / "hb")
    w = HeartbeatWriter(hb, process_index=0, interval_s=0.0, world=4,
                        epoch=0)
    w.beat(5, force=True)
    # atomic rewrite: no tmp litter, file parses whole
    assert not [n for n in os.listdir(hb) if ".tmp" in n]
    beats = read_heartbeats(hb)
    assert beats[0]["step"] == 5 and beats[0]["world"] == 4
    assert beats[0]["epoch"] == 0
    # re-mesh bumps the incarnation; the epoch fence hides the old beats
    w.set_membership(world=3, epoch=1)
    w.beat(7, force=True)
    assert read_heartbeats(hb, min_epoch=1)[0]["world"] == 3
    stale = HeartbeatWriter(hb, process_index=1, interval_s=0.0, world=4,
                            epoch=0)
    stale.beat(5, force=True)
    fenced = read_heartbeats(hb, min_epoch=1)
    assert 0 in fenced and 1 not in fenced  # prior incarnation never live
    # a restarted incarnation inherits the file's history tail
    w2 = HeartbeatWriter(hb, process_index=0, interval_s=0.0)
    w2.beat(8, force=True)
    lines = open(os.path.join(hb, "heartbeat-00000.jsonl")).read()
    assert lines.count("\n") >= 3


def test_coordinator_evicts_dead_admits_joins(tmp_path):
    hb = str(tmp_path / "hb")
    co = el.ElasticCoordinator(hb, world=4, min_ranks=2, max_age_s=60.0)
    now = time.time()

    def beats(ages, missing=()):
        return {r: {"pid": r, "step": 10, "t": now - ages.get(r, 0.0),
                    "epoch": co.membership().epoch}
                for r in range(4) if r not in missing}

    # all fresh: no decision, membership file untouched
    assert co.decide(now=now, beats=beats({})) is None
    assert co.membership() == el.Membership(0, (0, 1, 2, 3))
    # one stale beat: evicted, epoch bumps, commit is atomic + persistent
    chg = co.decide(now=now, beats=beats({3: 300.0}))
    assert (chg.kind, chg.new.ranks, chg.new.epoch) == ("shrink", (0, 1, 2), 1)
    assert "evict rank 3" in chg.reason
    assert el.ElasticCoordinator(hb, world=4).membership() == chg.new
    # a member with NO beat at the current epoch is in flight, not dead
    assert co.decide(now=now, beats=beats({}, missing=(1,))) is None
    # join protocol: request file -> admitted -> file consumed
    co.request_join(3)
    assert co.pending_joins() == {3}
    chg = co.decide(now=now, beats=beats({}))
    assert (chg.kind, chg.new.ranks, chg.new.epoch) == ("grow", (0, 1, 2, 3), 2)
    assert not os.path.exists(co.join_path(3))
    # min-ranks floor: refusing leaves membership (and epoch) in place
    chg = co.decide(now=now, beats=beats({0: 300.0, 1: 300.0, 2: 300.0}))
    assert chg is None
    assert co.membership().epoch == 2


def test_coordinator_liveness_matches_monitor(tmp_path):
    """decide() must classify with find_stragglers itself (no second
    threshold implementation): a slow-but-beating rank stays a member."""
    hb = str(tmp_path / "hb")
    co = el.ElasticCoordinator(hb, world=3, min_ranks=1, max_age_s=60.0)
    now = time.time()
    beats = {0: {"pid": 0, "step": 10, "t": now, "epoch": 0, "ema": 0.1},
             1: {"pid": 1, "step": 10, "t": now, "epoch": 0, "ema": 0.1},
             2: {"pid": 2, "step": 5, "t": now, "epoch": 0, "ema": 5.0}}
    flagged = find_stragglers(beats, now=now)
    assert 2 in flagged and "slow rank" in flagged[2]
    assert co.decide(now=now, beats=beats) is None  # slow != evicted


# -------------------------------------------------- re-grid exactness

def _toy_params():
    rng = np.random.default_rng(0)
    return {
        "dense": {"kernel": rng.normal(size=(7, 13)).astype(np.float32),
                  "bias": rng.normal(size=(13,)).astype(np.float32)},
        "head": {"kernel": rng.normal(size=(13, 3)).astype(np.float32)},
    }


def _wus_momentum_like(params, n, block, rng, quantized):
    """Momentum in the stacked WUS layout with real (non-zero) content:
    a param-shaped random vector laid flat, zero-padded to whole chunks —
    exactly what init_wus_momentum + training produces."""
    from pytorch_distributed_tpu.parallel import zero as zero_lib

    def stack(p):
        size = int(np.prod(p.shape))
        chunk = zero_lib.chunk_size(size, n, block)
        flat = np.zeros(n * chunk, np.float32)
        flat[:size] = rng.normal(size=(size,)).astype(np.float32)
        return flat.reshape(n, chunk)

    out = {"buf": jax.tree_util.tree_map(stack, params)}
    if quantized:
        out["agerr"] = jax.tree_util.tree_map(stack, params)
    return out


@pytest.mark.parametrize("quantized", [False, True])
def test_regrid_wus_momentum_roundtrip_bit_exact(quantized):
    from pytorch_distributed_tpu.ops import qcomm
    from pytorch_distributed_tpu.parallel import zero as zero_lib

    params = _toy_params()
    rng = np.random.default_rng(1)
    blk = qcomm.DEFAULT_BLOCK
    m4 = _wus_momentum_like(params, 4, blk, rng, quantized)
    m2 = el.regrid_wus_momentum(m4, params, 2)
    m4b = el.regrid_wus_momentum(m2, params, 4)
    for k in m4:
        a = jax.tree_util.tree_leaves(m4[k])
        b = jax.tree_util.tree_leaves(m4b[k])
        for la, lb in zip(a, b):
            np.testing.assert_array_equal(la, lb)  # bit-exact N->M->N
    # and the regridded state still gathers to the same full momentum
    g4 = zero_lib.gather_momentum(m4, params)
    g2 = zero_lib.gather_momentum(m2, params)
    for la, lb in zip(jax.tree_util.tree_leaves(g4),
                      jax.tree_util.tree_leaves(g2)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    # shapes actually re-chunked for the new world
    for leaf in jax.tree_util.tree_leaves(m2["buf"]):
        assert leaf.shape[0] == 2


def test_regrid_wus_rejects_non_wus_layout():
    with pytest.raises(ValueError, match="WUS layout"):
        el.regrid_wus_momentum({"nope": 1}, {"p": np.zeros(3)}, 2)


def test_regrid_stacked_residual_preserves_sum():
    rng = np.random.default_rng(2)
    res = {"conv": rng.normal(size=(4, 3, 5)).astype(np.float32)}
    out = el.regrid_stacked_residual(res, 2)
    leaf = out["conv"]
    assert leaf.shape == (2, 3, 5)
    # the collective sums per-rank contributions: the sum over slots is
    # the semantic content, carried whole in slot 0
    np.testing.assert_allclose(leaf[0], res["conv"].sum(axis=0), rtol=1e-6)
    np.testing.assert_array_equal(leaf[1], np.zeros((3, 5), np.float32))
    np.testing.assert_allclose(out["conv"].sum(axis=0),
                               res["conv"].sum(axis=0), rtol=1e-6)


# ------------------------------------------------- the LM chaos drill

def _lm_drill(tmp_path, tag, elastic=None, chaos=None):
    from pytorch_distributed_tpu.models.transformer import TransformerLM
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
    from pytorch_distributed_tpu.train.lm import (
        LMTrainer,
        SyntheticTokenDataset,
    )

    mesh = build_mesh(MeshSpec(("data",), (4,)), jax.devices()[:4])
    model = TransformerLM(vocab_size=64, d_model=32, n_heads=2, n_layers=1)
    ds = SyntheticTokenDataset(256, 16, 64, seed=0)
    mpath = str(tmp_path / f"metrics-{tag}.jsonl")
    with mesh:
        # batch 12 divides every admissible world (4, 3, 2)
        t = LMTrainer(model, mesh, ds, batch_size=12, lr=1e-2, seed=0,
                      eval_dataset=None, save_steps=2, prefetch=0,
                      metrics_jsonl=mpath, goodput=True,
                      hb_dir=str(tmp_path / f"hb-{tag}"),
                      elastic=elastic, chaos=chaos)
        loss = t.fit(12, print_freq=4)
    return t, loss, mpath


def test_lm_elastic_shrink_grow_parity(tmp_path):
    """The acceptance drill: world 4 loses rank 3 at step 4 (re-mesh to 3,
    rewind to the last keeper snapshot), re-admits it at step 8 (re-mesh
    back to 4), and the final loss matches the uninterrupted world-4 run
    within the pinned fence — membership epochs, remesh ft_events, and
    goodput's remesh badput booking all checked on the way."""
    from pytorch_distributed_tpu.ft import (
        ChaosSchedule,
        ElasticSim,
        JoinRankAt,
        LoseRankAt,
    )
    from pytorch_distributed_tpu.obs.goodput import compute_goodput

    _, loss_ref, _ = _lm_drill(tmp_path, "ref")

    sim = ElasticSim(world=4, min_ranks=2)
    chaos = ChaosSchedule(LoseRankAt(4, rank=3, reason="drill"),
                          JoinRankAt(8, rank=3, reason="drill"))
    t, loss, mpath = _lm_drill(tmp_path, "elastic", elastic=sim, chaos=chaos)

    # membership: shrink then grow, epochs 1 and 2, back to world 4
    assert [(c.kind, c.old.world, c.new.world, c.new.epoch)
            for c in sim.history] == [("shrink", 4, 3, 1), ("grow", 3, 4, 2)]
    assert dict(t.mesh.shape)["data"] == 4
    assert t._membership_epoch == 2

    # the remesh trail: ft_events with the full rescale accounting
    recs = [json.loads(ln) for ln in open(mpath)]
    ev = [r for r in recs if r.get("ft_event") == "remesh"]
    assert [(e["change"], e["old_world"], e["new_world"], e["epoch"])
            for e in ev] == [("shrink", 4, 3, 1), ("grow", 3, 4, 2)]
    for e in ev:
        assert "drill" in e["reason"] and e["rescale"] == "none"

    # goodput books the re-mesh gaps as their own badput class
    rep = compute_goodput(recs)
    assert rep.counts["remesh"] == 2
    assert rep.badput_s["remesh"] > 0.0

    # heartbeats carry the final incarnation (world 4, epoch 2)
    beats = read_heartbeats(str(tmp_path / "hb-elastic"))
    assert beats[0]["epoch"] == 2 and beats[0]["world"] == 4

    # the parity fence (RESULTS_elastic.json): rescale "none" + snapshot
    # rewind replay the identical global batch sequence, so the drill's
    # loss is bit-for-bit the uninterrupted run's
    fence = json.load(open(os.path.join(REPO, "RESULTS_elastic.json")))
    tol = fence["fence"]["loss_delta_max"]
    assert abs(loss - loss_ref) <= tol, (loss, loss_ref, tol)


def test_lm_trainer_rejects_bad_rescale_rule(lm_world32):
    """The rule is validated at construction (before any compile), so a
    typo'd --rescale-lr dies at startup, not at the first re-mesh."""
    from pytorch_distributed_tpu.train.lm import LMTrainer

    mesh, model, ds = lm_world32
    with mesh, pytest.raises(ValueError, match="rescale_lr"):
        LMTrainer(model, mesh, ds, batch_size=8, eval_dataset=None,
                  rescale_lr="bogus")


# ------------------------------------------------- slow: image drill

@pytest.mark.slow
def test_image_elastic_drill_explicit_wus_int8(tmp_path):
    """The kitchen-sink image drill: explicit collectives + int8 gradient
    compression (stacked error-feedback residual) + ZeRO-WUS momentum
    shards, through a shrink AND a grow — every re-grid surgery the
    re-mesh performs, exercised in one run (slow: resnet18 compiles
    ~20s/world on the 1-core host)."""
    from pytorch_distributed_tpu.ft import (
        ChaosSchedule,
        ElasticSim,
        JoinRankAt,
        LoseRankAt,
    )
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
    from pytorch_distributed_tpu.parallel import zero as zero_lib
    from pytorch_distributed_tpu.train.config import Config
    from pytorch_distributed_tpu.train.trainer import Trainer

    mpath = str(tmp_path / "m.jsonl")
    cfg = Config(arch="resnet18", batch_size=12, epochs=1, lr=0.1,
                 print_freq=4, synthetic=True, synthetic_length=144,
                 image_size=32, num_classes=4, seed=0, workers=0,
                 checkpoint_dir=str(tmp_path / "ckpt"), save_steps=2,
                 metrics_jsonl=mpath, goodput=True,
                 elastic=True, min_ranks=2, rescale_lr="none")
    mesh = build_mesh(MeshSpec(("data",), (4,)), jax.devices()[:4])
    chaos = ChaosSchedule(LoseRankAt(4, rank=3), JoinRankAt(8, rank=3))
    t = Trainer(cfg, mesh=mesh, explicit_collectives=True,
                grad_compress="int8", zero="wus", chaos=chaos)
    assert isinstance(t.elastic, ElasticSim)  # wired from cfg
    t.fit()

    recs = [json.loads(ln) for ln in open(mpath)]
    ev = [r for r in recs if r.get("ft_event") == "remesh"]
    assert [(e["change"], e["old_world"], e["new_world"]) for e in ev] == \
        [("shrink", 4, 3), ("grow", 3, 4)]
    assert dict(t.mesh.shape)["data"] == 4
    # the WUS momentum and stacked residual were re-gridded 4->3->4
    assert zero_lib.is_wus_momentum(t.state.momentum)
    for leaf in jax.tree_util.tree_leaves(t.state.momentum):
        assert leaf.shape[0] == 4
    for leaf in jax.tree_util.tree_leaves(t.state.residual):
        assert leaf.shape[0] == 4


# ------------------------- slow: cross-process coordinator drill

_BEATER = textwrap.dedent(
    """
    import json, os, sys, time
    rank = int(sys.argv[1]); hb = sys.argv[2]; epoch = int(sys.argv[3])
    sys.path.insert(0, %(repo)r)
    from pytorch_distributed_tpu.ft.elastic import MEMBERSHIP_NAME
    from pytorch_distributed_tpu.obs.heartbeat import HeartbeatWriter
    w = HeartbeatWriter(hb, process_index=rank, interval_s=0.0, world=2,
                        epoch=epoch)
    mpath = os.path.join(hb, MEMBERSHIP_NAME)
    for step in range(2000):
        # a live worker re-reads the membership each beat so its beats
        # are stamped with the current incarnation
        try:
            m = json.load(open(mpath))
            w.set_membership(world=len(m["ranks"]), epoch=m["epoch"])
        except (OSError, ValueError, KeyError):
            pass
        w.beat(step, force=True, step_time_ema=0.1)
        time.sleep(0.2)
    """
)


def _agent(hb, *args, **kw):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PTD_TPU", "JAX_", "XLA_"))}
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "elastic_agent.py"),
         *args, "--hb-dir", hb, "--world", "2", "--min-ranks", "1",
         "--max-age-s", "2.0"],
        capture_output=True, text=True, timeout=120, env=env, **kw)


@pytest.mark.slow
def test_multiprocess_agent_evicts_and_readmits(tmp_path):
    """The file-protocol drill across REAL processes: two beating workers,
    one SIGKILLed; scripts/elastic_agent.py (the login-node CLI) evicts it
    on liveness, the restarted worker files a join request, and the next
    coordination round re-admits it — end to end through the same
    membership.json + heartbeat files a fleet would share."""
    hb = str(tmp_path / "hb")
    script = tmp_path / "beater.py"
    script.write_text(_BEATER % {"repo": REPO})
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PTD_TPU", "JAX_", "XLA_"))}

    def beater(rank, epoch):
        return subprocess.Popen(
            [sys.executable, str(script), str(rank), hb, str(epoch)],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env)

    procs = {r: beater(r, 0) for r in (0, 1)}
    try:
        time.sleep(1.5)  # both ranks beating
        st = _agent(hb, "status")
        assert st.returncode == 0, st.stdout + st.stderr

        # rank 1 dies hard; after max-age its beat is stale
        procs[1].send_signal(signal.SIGKILL)
        procs[1].wait()
        time.sleep(3.0)
        watch = _agent(hb, "watch", "--once")
        assert "shrink" in watch.stdout, watch.stdout + watch.stderr
        m = json.load(open(os.path.join(hb, "membership.json")))
        assert (m["epoch"], m["ranks"]) == (1, [0])

        # the replacement restarts at the new epoch and asks to join
        join = _agent(hb, "join", "--rank", "1")
        assert join.returncode == 0, join.stdout + join.stderr
        procs[1] = beater(1, 1)
        time.sleep(1.0)
        watch = _agent(hb, "watch", "--once")
        assert "grow" in watch.stdout, watch.stdout + watch.stderr
        m = json.load(open(os.path.join(hb, "membership.json")))
        assert (m["epoch"], m["ranks"]) == (2, [0, 1])

        time.sleep(1.0)  # both beat at epoch 2
        st = _agent(hb, "status")
        assert st.returncode == 0, st.stdout + st.stderr
        assert "epoch 2" in st.stdout
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
                p.wait()
