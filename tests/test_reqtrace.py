"""Per-request tracing + SLO attribution plane (ISSUE 17).

The load-bearing guarantees: tracing is *invisible* to the served tokens
(bit-exact on/off), every recorded request's attributed components sum
back to its engine-stamped TTFT/e2e exactly, the span buffer is bounded
with explicit drop accounting, tail-based sampling never loses an SLO
violator, and the preempt-redo spans the tracer books agree with the
scheduler's own preemption counter.  The CLI/report half rides on a
checked-in fixture (tests/data/reqtrace_fixture.jsonl) so the jax-free
``obs_trace.py`` path and the ``obs_report --diff`` attribution rows are
exercised exactly as a user would hit them.

All engine tests run on a fake clock (time_fn/sleep_fn injection), so
they are deterministic and never actually sleep.
"""

import importlib.util
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from pytorch_distributed_tpu.obs.reqtrace import (
    TTFT_COMPONENTS,
    ReqTracer,
    TraceContext,
    attribution_summary,
    chrome_events,
    tail_attribution,
    trace_records,
)
from pytorch_distributed_tpu.serving.engine import (
    ServingEngine,
    init_lm_params,
)
from pytorch_distributed_tpu.serving.scheduler import Request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "data", "reqtrace_fixture.jsonl")

CFG = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=2)
BS = 8


def _params(seed=0):
    return init_lm_params(block_size=BS, seed=seed, **CFG)


def _fake_clock():
    t = [0.0]
    return (lambda: t[0]), (lambda s: t.__setitem__(0, t[0] + max(s, 1e-3)))


def _engine(params, **kw):
    time_fn, sleep_fn = _fake_clock()
    defaults = dict(max_batch=4, kv_blocks=17, block_size=BS,
                    blocks_per_seq=8, chunk_size=8, max_new_tokens=64,
                    time_fn=time_fn, sleep_fn=sleep_fn, seed=0, **CFG)
    defaults.update(kw)
    return ServingEngine(params, **defaults)


def _storm_load(n=4):
    return [(0.0, Request(rid=i, prompt=[i + 1, i + 2, i + 3, i + 4],
                          max_new_tokens=20)) for i in range(n)]


def _fixture_records():
    with open(FIXTURE) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


# ------------------------------------------------------------ span lifecycle

def test_span_lifecycle_and_exact_attribution():
    """Manual clock through the full hook sequence: the five TTFT
    components must sum back to the TTFT *exactly* (same engine clock on
    both sides — this is an identity, not an estimate)."""
    tr = ReqTracer(slo_ms=15.0, sample=0.0)
    ctx = tr.on_submit(7, 1.000, priority=2)
    assert ctx.rid == 7 and ctx.hops == ["engine:0"]
    assert ctx.trace_id.startswith("ptd-engine:0-")
    tr.on_admit(7, 1.010)
    tr.on_prefill(7, [1.010, 1.015, 1.020], redo=False)
    tr.on_emit(7, 1.020, first=True)
    tr.on_decode(7, 1.020, 1.028, n_tokens=8)
    tr.on_complete(7, 1.030, tokens=9, preemptions=0)

    (ev,) = tr.drain()
    assert tr.drain() == []  # lazy flush: drained once, gone
    assert ev["ttft_ms"] == pytest.approx(20.0)
    assert ev["e2e_ms"] == pytest.approx(30.0)
    assert ev["queue_wait_ms"] == pytest.approx(10.0)
    assert ev["prefill_ms"] == pytest.approx(10.0)
    assert ev["other_wait_ms"] == pytest.approx(0.0)
    assert ev["decode_ms"] == pytest.approx(8.0)
    assert ev["other_run_ms"] == pytest.approx(2.0)
    assert ev["preempt_redo_ms"] == 0.0
    assert ev["queue_wait_share_pct"] == pytest.approx(50.0)
    # TTFT identity over the published component fields
    waits = (ev["queue_wait_ms"] + ev["prefill_ms"] + ev["redo_wait_ms"]
             + ev["defrag_wait_ms"] + ev["other_wait_ms"])
    assert waits == pytest.approx(ev["ttft_ms"], abs=1e-3)
    runs = (ev["decode_ms"] + ev["redo_own_ms"] + ev["defrag_run_ms"]
            + ev["other_run_ms"])
    assert runs == pytest.approx(ev["e2e_ms"] - ev["ttft_ms"], abs=1e-3)

    # 20ms TTFT > 15ms SLO: a violator keeps its spans even at sample=0
    assert ev["violated"] == 1 and ev["sampled"] == 1
    kinds = [s[0] for s in json.loads(ev["spans"])]
    assert kinds == ["submit", "queue", "prefill", "prefill", "emit",
                     "decode", "complete"]
    assert ev["n_spans"] == 7 and ev["spans_dropped"] == 0

    # explicit serializable context: the router-propagation handoff shape
    back = TraceContext.from_wire(json.loads(ev["ctx"]))
    assert (back.trace_id, back.rid, back.hops) == (
        ctx.trace_id, 7, ["engine:0"])
    assert back.submit_t == pytest.approx(1.000)

    sf = tr.step_fields()
    assert sf["trace_completed"] == 1.0
    assert sf["queue_wait_share_p99"] == pytest.approx(50.0)
    assert sf["preempt_redo_ms_p99"] == 0.0


def test_defrag_pause_attributed_out_of_queue_wait():
    """A defrag pause overlapping a request's queue window must move out
    of queue_wait and into defrag_wait — that's the whole point of the
    attribution (the queue didn't stall, the pool compaction did)."""
    tr = ReqTracer(sample=1.0)
    tr.on_submit(3, 2.000)
    tr.on_defrag(2.002, 2.006)
    tr.on_admit(3, 2.010)
    tr.on_prefill(3, [2.010, 2.012], redo=False)
    tr.on_complete(3, 2.012, tokens=1, preemptions=0)
    (ev,) = tr.drain()
    assert ev["defrag_wait_ms"] == pytest.approx(4.0)
    assert ev["queue_wait_ms"] == pytest.approx(6.0)
    assert ev["ttft_ms"] == pytest.approx(12.0)


# ------------------------------------------------------- bounded buffer

def test_bounded_span_buffer_drop_accounting():
    """Flight-recorder discipline: the span ring never exceeds
    max_spans, drops are *counted* (per record and globally), and the
    budget is released when a record completes."""
    tr = ReqTracer(sample=1.0, max_spans=4)
    tr.on_submit(0, 0.0)
    tr.on_admit(0, 0.001)
    tr.on_prefill(0, [0.001, 0.002, 0.003, 0.004], redo=False)  # 3 spans
    tr.on_decode(0, 0.004, 0.005, 1)        # over budget: dropped
    tr.on_complete(0, 0.005, tokens=4, preemptions=0)
    (ev,) = tr.drain()
    assert ev["n_spans"] <= 4
    assert ev["spans_dropped"] >= 2        # 3rd chunk + decode (+complete)
    assert ev["spans_dropped"] == tr.spans_dropped
    # attribution is span-drop-proof: it rides on scalars, not the ring
    assert ev["prefill_ms"] == pytest.approx(3.0)
    assert ev["decode_ms"] == pytest.approx(1.0)

    # budget released: a fresh request records spans again
    tr.on_submit(1, 1.0)
    tr.on_admit(1, 1.001)
    tr.on_prefill(1, [1.001, 1.002], redo=False)
    tr.on_complete(1, 1.002, tokens=1, preemptions=0)
    (ev2,) = tr.drain()
    kinds = [s[0] for s in json.loads(ev2["spans"])]
    assert kinds == ["submit", "queue", "prefill", "complete"]
    assert ev2["spans_dropped"] == 0


def test_bounded_pending_queue_drops_records():
    tr = ReqTracer(sample=0.0, max_pending=1)
    for rid in range(3):
        tr.on_submit(rid, 0.0)
        tr.on_admit(rid, 0.001)
        tr.on_prefill(rid, [0.001, 0.002], redo=False)
        tr.on_complete(rid, 0.002, tokens=1, preemptions=0)
    assert tr.records_dropped == 2
    assert len(tr.drain()) == 1
    assert tr.completed == 3  # counters still see every completion


# ------------------------------------------------------- tail sampling

def test_tail_sampling_keeps_every_violator():
    """sample=0.0 drops all span payloads *except* SLO violators' — the
    tail is exactly what you need the geometry for."""
    tr = ReqTracer(slo_ms=20.0, sample=0.0)
    for rid in range(6):
        slow = rid % 2 == 1
        tr.on_submit(rid, 0.0)
        tr.on_admit(rid, 0.040 if slow else 0.004)
        t0 = 0.040 if slow else 0.004
        tr.on_prefill(rid, [t0, t0 + 0.002], redo=False)
        tr.on_complete(rid, t0 + 0.003, tokens=1, preemptions=0)
    recs = tr.drain()
    assert tr.violations == 3
    for r in recs:
        if r["violated"]:
            assert r["sampled"] == 1 and "spans" in r
        else:
            assert r["sampled"] == 0 and "spans" not in r


def test_head_sampling_is_deterministic_knuth_hash():
    tr = ReqTracer(sample=0.5)
    kept = {}
    for rid in range(32):
        tr.on_submit(rid, 0.0)
        tr.on_admit(rid, 0.001)
        tr.on_prefill(rid, [0.001, 0.002], redo=False)
        tr.on_complete(rid, 0.002, tokens=1, preemptions=0)
    for r in tr.drain():
        kept[r["rid"]] = r["sampled"]
    for rid, sampled in kept.items():
        want = ((rid * 2654435761) & 0xFFFFFFFF) / 2 ** 32 < 0.5
        assert sampled == (1 if want else 0)
    assert 0 < sum(kept.values()) < 32  # the hash actually splits


# ------------------------------------------------ engine instrumentation

def test_tokens_bit_exact_with_tracing_on_and_off():
    """Tracing must be invisible: identical seeded load through an
    identical engine produces bit-identical tokens with the recorder on
    (sample=1.0, so every span path runs) and off."""
    params = _params()
    plain = _engine(params, kv_blocks=7, blocks_per_seq=4)
    plain.run(_storm_load())
    traced = _engine(params, kv_blocks=7, blocks_per_seq=4,
                     trace=ReqTracer(slo_ms=1.0, sample=1.0))
    s = traced.run(_storm_load())
    assert s["preemptions"] > 0  # the hard path: preempt/redo while traced
    assert ({r.rid: list(r.generated) for r in traced.finished}
            == {r.rid: list(r.generated) for r in plain.finished})


def test_redo_spans_match_scheduler_preemptions():
    """Every scheduler preemption forces exactly one recompute prefill —
    the tracer's redo_prefill span count must agree with the scheduler's
    own counter, or the attribution is fiction."""
    tr = ReqTracer(sample=1.0)
    eng = _engine(_params(), kv_blocks=7, blocks_per_seq=4, trace=tr)
    s = eng.run(_storm_load())
    assert s["completed"] == 4
    assert s["preemptions"] > 0
    assert tr.redo_prefills == s["preemptions"]
    recs = tr.drain()
    assert sum(r["preemptions"] for r in recs) == s["preemptions"]
    # a preempted request's span list shows the preempt → redo geometry
    # (durations can be 0 on the fake clock; the *structure* cannot lie)
    bumped = [r for r in recs if r["preemptions"] > 0]
    assert bumped
    for r in bumped:
        kinds = {sp[0] for sp in json.loads(r["spans"])}
        assert "preempt" in kinds and "redo_prefill" in kinds


def test_record_components_reconcile_with_engine_ttft():
    """±5% acceptance fence, enforced far tighter: every drained record's
    component sums must reconcile with its engine-stamped TTFT/e2e, and
    the record TTFTs must *be* the engine's own TTFT samples."""
    tr = ReqTracer(sample=0.0)
    eng = _engine(_params(), kv_blocks=7, blocks_per_seq=4, trace=tr)
    eng.run(_storm_load())
    recs = tr.drain()
    assert len(recs) == 4
    for r in recs:
        waits = (r["queue_wait_ms"] + r["prefill_ms"] + r["redo_wait_ms"]
                 + r["defrag_wait_ms"] + r["other_wait_ms"])
        assert waits == pytest.approx(r["ttft_ms"], abs=0.05)
        runs = (r["decode_ms"] + r["redo_own_ms"] + r["defrag_run_ms"]
                + r["other_run_ms"])
        assert runs == pytest.approx(r["e2e_ms"] - r["ttft_ms"], abs=0.05)
    got = sorted(round(r["ttft_ms"], 3) for r in recs)
    want = sorted(round(t * 1e3, 3) for t in eng.ttft_samples)
    assert got == want


def test_engine_books_reqtrace_ft_events_and_step_gauges(tmp_path):
    from pytorch_distributed_tpu.obs.metrics import (
        MetricsLogger,
        read_metrics,
    )

    path = str(tmp_path / "serve.jsonl")
    obs = MetricsLogger(path, flush_every=1)
    tr = ReqTracer(sample=0.0)
    eng = _engine(_params(), kv_blocks=7, blocks_per_seq=4, trace=tr,
                  obs=obs)
    eng.run(_storm_load())
    obs.close()
    records = read_metrics(path)
    recs = [r for r in records if r.get("ft_event") == "reqtrace"]
    assert len(recs) == 4
    steps = [r for r in records
             if r.get("serving") == 1.0 and "queue_wait_share_p99" in r]
    assert steps, "attribution gauges never reached the step records"
    assert all("preempt_redo_ms_p99" in r for r in steps)
    assert max(r["trace_completed"] for r in steps) == 4.0


# ------------------------------------------------------------- analysis

def test_analysis_rollup_on_engine_records(tmp_path):
    from pytorch_distributed_tpu.obs.metrics import (
        MetricsLogger,
        read_metrics,
    )

    path = str(tmp_path / "serve.jsonl")
    obs = MetricsLogger(path, flush_every=1)
    tr = ReqTracer(slo_ms=1.0, sample=1.0)
    eng = _engine(_params(), kv_blocks=7, blocks_per_seq=4, trace=tr,
                  obs=obs)
    s = eng.run(_storm_load())
    obs.close()
    trs = trace_records(read_metrics(path))
    assert len(trs) == 4
    summ = attribution_summary(trs)
    assert summ["requests"] == 4
    assert summ["preemptions"] == s["preemptions"]
    assert summ["recon_err_ms_max"] < 0.05
    tail = tail_attribution(trs, q=0.99)
    assert tail["dominant"] in TTFT_COMPONENTS
    assert set(tail["shares_pct"]) == set(TTFT_COMPONENTS)


def test_fixture_tail_names_preempt_redo_dominant():
    """The checked-in preemption-storm fixture: tail attribution must
    name preempt-redo as the dominant TTFT component."""
    trs = trace_records(_fixture_records())
    assert len(trs) == 24
    summ = attribution_summary(trs)
    assert summ["violations"] >= 1
    assert summ["recon_err_ms_max"] < 0.05
    assert summ["tail"]["dominant"] == "preempt_redo"
    assert summ["tail"]["shares_pct"]["preempt_redo"] > 50.0


def test_merged_timeline_grows_request_tracks():
    """to_chrome_trace(req_traces=...) merges per-request tracks beside
    the (empty here) step timeline: one tid per request, preempt spans
    categorized so Perfetto can color them."""
    from pytorch_distributed_tpu.obs.timeline import to_chrome_trace

    trs = trace_records(_fixture_records())
    doc = to_chrome_trace([], req_traces=trs)
    evs = doc["traceEvents"]
    procs = [e for e in evs if e.get("name") == "process_name"]
    assert any(e["args"]["name"] == "serving requests" for e in procs)
    threads = [e for e in evs if e.get("name") == "thread_name"]
    kept = [r for r in trs if r.get("spans")]
    assert len(threads) == len(kept) and kept
    kinds = {e["name"] for e in evs if e.get("ph") == "X"}
    assert {"queue", "prefill", "decode"} <= kinds
    assert "redo_prefill" in kinds  # it IS a storm fixture
    assert all(e["cat"] == "preempt" for e in evs
               if e.get("ph") == "X"
               and e["name"] in ("redo_prefill", "requeue_wait", "preempt"))


# ----------------------------------------------------------- CLI plane

def test_obs_trace_selftest_fixture_roundtrip():
    """The jax-free CLI's own selftest: fixture parse → attribution →
    chrome export → TraceContext wire round-trip, with the import-time
    jax-free guarantee asserted inside."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "obs_trace.py"),
         "--selftest"],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stdout + r.stderr
    assert "OK" in r.stdout


def test_obs_trace_json_on_fixture():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "obs_trace.py"),
         "--metrics-jsonl", FIXTURE, "--json"],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stdout + r.stderr
    out = json.loads(r.stdout)
    assert out["requests"] == 24
    assert out["tail"]["dominant"] == "preempt_redo"


def test_planted_attribution_regression_fails_diff(tmp_path):
    """A preemption storm that moves *only* the attribution rows (same
    tokens/s, same TTFT p99 stamps) must still flip obs_report --diff to
    exit 1 — that's the alarm this PR installs."""
    def write(path, share, redo):
        with open(path, "w") as f:
            for i in range(10):
                f.write(json.dumps({
                    "step": i, "t": float(i), "step_time": 0.005,
                    "n_items": 8, "serving": 1.0, "tokens_per_s": 512.0,
                    "ttft_p99_ms": 80.0, "queue_depth": 1.0,
                    "queue_wait_share_p99": share,
                    "preempt_redo_ms_p99": redo,
                }) + "\n")

    base, storm = str(tmp_path / "base.jsonl"), str(tmp_path / "storm.jsonl")
    write(base, share=12.0, redo=0.0)
    write(storm, share=55.0, redo=210.0)
    cmd = [sys.executable, os.path.join(REPO, "scripts", "obs_report.py")]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    r = subprocess.run(cmd + ["--diff", base, storm],
                       capture_output=True, text=True, cwd=REPO, env=env)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "queue_wait_share_p99" in r.stdout
    assert "preempt_redo_ms_p99" in r.stdout
    # the reverse direction is an improvement, not a regression
    r2 = subprocess.run(cmd + ["--diff", storm, base],
                        capture_output=True, text=True, cwd=REPO, env=env)
    assert r2.returncode == 0, r2.stdout + r2.stderr


# ------------------------------------------------- checkpoint → serving

def _torch_style_lm_state_dict(vocab=64, d_model=32, n_layers=2, seed=3):
    rng = np.random.default_rng(seed)

    def w(*shape):
        return (rng.standard_normal(shape) * 0.02).astype(np.float32)

    sd = {"embed.weight": w(vocab, d_model),
          "ln_f.weight": np.ones(d_model, np.float32),
          "ln_f.bias": np.zeros(d_model, np.float32)}
    for i in range(n_layers):
        p = f"blocks.{i}."
        sd[p + "ln1.weight"] = np.ones(d_model, np.float32)
        sd[p + "ln1.bias"] = np.zeros(d_model, np.float32)
        sd[p + "ln2.weight"] = np.ones(d_model, np.float32)
        sd[p + "ln2.bias"] = np.zeros(d_model, np.float32)
        sd[p + "attn.qkv.weight"] = w(3 * d_model, d_model)
        sd[p + "attn.proj.weight"] = w(d_model, d_model)
        sd[p + "fc1.weight"] = w(4 * d_model, d_model)
        sd[p + "fc1.bias"] = np.zeros(4 * d_model, np.float32)
        sd[p + "fc2.weight"] = w(d_model, 4 * d_model)
        sd[p + "fc2.bias"] = np.zeros(d_model, np.float32)
    sd["head.weight"] = sd["embed.weight"]  # tied
    return sd


def test_checkpoint_import_roundtrip_serves_with_int8(tmp_path):
    """Satellite: torch-naming LM state_dict → import → msgpack →
    serve_lm --checkpoint, with --quant int8 composing on the imported
    tree.  The quantized run must emit the same tokens whether params
    arrive via the checkpoint or directly — the import is a no-op."""
    from pytorch_distributed_tpu.utils.torch_import import (
        import_torch_checkpoint,
        save_as_pretrained,
    )

    # scripts/ is not a package; load the serving front end by path
    spec = importlib.util.spec_from_file_location(
        "serve_lm_ckpt", os.path.join(REPO, "scripts", "serve_lm.py"))
    serve_lm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(serve_lm)
    load_checkpoint_params = serve_lm.load_checkpoint_params

    sd = _torch_style_lm_state_dict()
    variables, meta = import_torch_checkpoint(
        {"state_dict": sd, "arch": "lm_tiny", "epoch": 3})
    assert meta["arch"] == "lm_tiny"
    assert "embed" in variables["params"]
    # torch Linear stores [out, in]; ours is [in, out]
    assert variables["params"]["block_0"]["attn"]["qkv"]["kernel"].shape \
        == (32, 96)

    path = save_as_pretrained(str(tmp_path), "lm_tiny", variables, meta)
    params, vocab, d_model, n_layers = load_checkpoint_params(path)
    assert (vocab, d_model, n_layers) == (64, 32, 2)
    np.testing.assert_array_equal(
        np.asarray(params["embed"]["embedding"]), sd["embed.weight"])

    from pytorch_distributed_tpu.models.quant import quantize_lm_params

    direct = _engine(quantize_lm_params(variables["params"]), quant="int8")
    direct.run(_storm_load(2))
    via_ckpt = _engine(quantize_lm_params(params), quant="int8")
    s = via_ckpt.run(_storm_load(2))
    assert s["completed"] == 2
    assert ({r.rid: list(r.generated) for r in via_ckpt.finished}
            == {r.rid: list(r.generated) for r in direct.finished})


def test_lm_import_rejects_untied_head():
    from pytorch_distributed_tpu.utils.torch_import import (
        import_lm_state_dict,
    )

    sd = _torch_style_lm_state_dict()
    sd["head.weight"] = sd["head.weight"] + 1.0
    with pytest.raises(ValueError, match="tied"):
        import_lm_state_dict(sd)
