"""Ulysses-style all-to-all SP vs dense oracle on ('data', 'seq') meshes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
from pytorch_distributed_tpu.parallel.ring import dense_attention
from pytorch_distributed_tpu.parallel.ulysses import a2a_self_attention


def _qkv(B=2, L=32, H=8, D=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(B, L, H, D)).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("mesh_shape", [("seq", 8), ("data_seq", None)])
def test_a2a_matches_dense(causal, mesh_shape):
    if mesh_shape[0] == "seq":
        mesh = build_mesh(MeshSpec(("seq",), (8,)), jax.devices()[:8])
    else:
        mesh = build_mesh(MeshSpec(("data", "seq"), (2, 4)), jax.devices()[:8])
    q, k, v = _qkv()
    want = dense_attention(q, k, v, causal=causal)
    got = a2a_self_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_a2a_gradients_match_dense():
    mesh = build_mesh(MeshSpec(("seq",), (8,)), jax.devices()[:8])
    q, k, v = _qkv(L=16)

    def loss_a2a(q, k, v):
        return jnp.sum(a2a_self_attention(q, k, v, mesh, causal=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    ga = jax.grad(loss_a2a, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(ga, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5)


def test_a2a_composes_with_model_axis():
    """(data, seq, model) mesh: heads sharded over model, further split
    across seq by the all-to-all — matches dense on the full arrays."""
    mesh = build_mesh(MeshSpec(("data", "seq", "model"), (2, 2, 2)),
                      jax.devices()[:8])
    q, k, v = _qkv(H=8)  # 8 heads / (model 2) = 4 local, / (seq 2) = 2
    want = dense_attention(q, k, v, causal=True)
    got = a2a_self_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_a2a_rejects_indivisible_heads():
    mesh = build_mesh(MeshSpec(("seq",), (8,)), jax.devices()[:8])
    q, k, v = _qkv(H=4)  # 4 heads over an 8-way seq axis
    with pytest.raises(ValueError, match="divisible"):
        a2a_self_attention(q, k, v, mesh, causal=True)


def test_a2a_bf16_inputs():
    mesh = build_mesh(MeshSpec(("seq",), (8,)), jax.devices()[:8])
    q, k, v = _qkv()
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    got = a2a_self_attention(qb, kb, vb, mesh, causal=True)
    assert got.dtype == jnp.bfloat16
    want = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(got, dtype=np.float32), np.asarray(want), rtol=5e-2,
        atol=5e-2)


def test_a2a_flash_inner_matches_dense():
    """The module's reason-to-exist executed: ``inner='flash'`` runs the
    Pallas kernel (interpret mode on CPU — the identical code path compiled
    on TPU) on the full gathered sequence at a 1024-aligned L, inside the
    same two all-to-alls, and matches the dense oracle — forward AND
    gradients (the fused dq / dk/dv backward kernels)."""
    mesh = build_mesh(MeshSpec(("seq",), (8,)), jax.devices()[:8])
    q, k, v = _qkv(B=1, L=1024, H=8, D=16, seed=3)
    want = dense_attention(q, k, v, causal=True)
    got = a2a_self_attention(q, k, v, mesh, causal=True, inner="flash")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    def loss_flash(q, k, v):
        return jnp.sum(
            a2a_self_attention(q, k, v, mesh, causal=True, inner="flash")
            ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4, err_msg=f"d{name}")


def test_pick_attention_impl_policy(monkeypatch):
    """The shared 'auto' policy both SelfAttention and the a2a inner use."""
    from pytorch_distributed_tpu.ops import flash_attention as fa

    # Explicit choices always pass through.
    assert fa.pick_attention_impl(32, "flash") == "flash"
    assert fa.pick_attention_impl(8192, "dense") == "dense"
    # Off-TPU, auto is always dense (interpret-mode flash is a test tool,
    # not a perf win).
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    assert fa.pick_attention_impl(8192, "auto") == "dense"
    # On TPU: flash at long, 1024-aligned L; dense otherwise.
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert fa.pick_attention_impl(4096, "auto") == "flash"
    assert fa.pick_attention_impl(8192, "auto") == "flash"
    assert fa.pick_attention_impl(2048, "auto") == "dense"   # below cutover
    assert fa.pick_attention_impl(4096 + 512, "auto") == "dense"  # unaligned


def test_lm_pretrain_sp_a2a_runs_and_learns(capsys, tmp_path):
    from pytorch_distributed_tpu.recipes import lm_pretrain

    final = lm_pretrain.main([
        "--vocab", "32", "--d-model", "32", "--n-heads", "8",
        "--n-layers", "1", "--seq-len", "32", "-b", "8",
        "--steps", "15", "--lr", "0.05", "-p", "4",
        "--dataset-length", "8", "--precision", "fp32",
        "--sp", "2", "--sp-impl", "a2a", "--no-eval",
        "--checkpoint-dir", str(tmp_path),
    ])
    out = capsys.readouterr().out
    first = float(out.split("Loss ")[1].split(" ")[0])
    assert final < first


def test_lm_pretrain_a2a_head_constraint():
    from pytorch_distributed_tpu.recipes import lm_pretrain

    with pytest.raises(SystemExit, match="divisible"):
        lm_pretrain.main([
            "--n-heads", "6", "--sp", "4", "--sp-impl", "a2a",
            "--steps", "1",
        ])
