"""KV-cached decode: incremental logits ≡ full forward; greedy generation."""

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_tpu.models.generate import greedy_generate
from pytorch_distributed_tpu.models.transformer import TransformerLM

CFG = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=2)


def _trained_params(seed=0):
    model = TransformerLM(**CFG)
    tokens = jnp.zeros((1, 16), jnp.int32)
    return model.init(jax.random.PRNGKey(seed), tokens)["params"]


def test_decode_mode_matches_full_forward():
    """Teacher-forcing consistency: prefill+incremental logits must equal
    the non-cached forward at every position."""
    params = _trained_params()
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, 64, size=(2, 12)).astype(np.int32))

    full = TransformerLM(**CFG).apply({"params": params}, tokens)

    dec_model = TransformerLM(**CFG, decode=True, max_len=12)
    cache = dec_model.init(jax.random.PRNGKey(0), tokens)["cache"]
    # prefill the first 4 tokens at once, then one token at a time
    logits_parts = []
    out, mut = dec_model.apply(
        {"params": params, "cache": cache}, tokens[:, :4], mutable=["cache"]
    )
    logits_parts.append(out)
    cache = mut["cache"]
    for t in range(4, 12):
        out, mut = dec_model.apply(
            {"params": params, "cache": cache}, tokens[:, t:t + 1],
            mutable=["cache"],
        )
        logits_parts.append(out)
        cache = mut["cache"]
    inc = jnp.concatenate(logits_parts, axis=1)
    np.testing.assert_allclose(np.asarray(inc), np.asarray(full),
                               rtol=2e-4, atol=2e-4)


def test_greedy_generate_matches_stepwise_argmax():
    """Generated tokens must equal running the full model autoregressively
    with argmax at each step (the no-cache oracle)."""
    params = _trained_params(seed=1)
    rng = np.random.default_rng(1)
    prompt = jnp.asarray(rng.integers(0, 64, size=(2, 5)).astype(np.int32))
    n_new = 6

    got = greedy_generate(params, prompt, n_new, **CFG)
    assert got.shape == (2, n_new) and got.dtype == jnp.int32

    model = TransformerLM(**CFG)
    seq = prompt
    want = []
    for _ in range(n_new):
        logits = model.apply({"params": params}, seq)
        tok = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
        want.append(tok)
        seq = jnp.concatenate([seq, tok[:, None]], axis=1)
    want = jnp.stack(want, axis=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_generate_single_token():
    params = _trained_params(seed=2)
    prompt = jnp.zeros((1, 3), jnp.int32)
    out = greedy_generate(params, prompt, 1, **CFG)
    assert out.shape == (1, 1)


def test_sampled_generation_temperature_and_topk():
    from pytorch_distributed_tpu.models.generate import generate

    params = _trained_params(seed=3)
    prompt = jnp.zeros((2, 4), jnp.int32)
    # temperature=0 == greedy
    g0 = generate(params, prompt, 5, **CFG, temperature=0.0)
    gg = greedy_generate(params, prompt, 5, **CFG)
    np.testing.assert_array_equal(np.asarray(g0), np.asarray(gg))
    # sampling is reproducible per seed and varies across seeds
    s1 = generate(params, prompt, 5, **CFG, temperature=1.5, seed=1)
    s1b = generate(params, prompt, 5, **CFG, temperature=1.5, seed=1)
    s2 = generate(params, prompt, 5, **CFG, temperature=1.5, seed=2)
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s1b))
    assert (np.asarray(s1) != np.asarray(s2)).any()
    # top-k=1 collapses sampling back to greedy
    k1 = generate(params, prompt, 5, **CFG, temperature=1.0, top_k=1, seed=7)
    np.testing.assert_array_equal(np.asarray(k1), np.asarray(gg))


def test_nucleus_sampling():
    from pytorch_distributed_tpu.models.generate import generate

    params = _trained_params(seed=3)
    prompt = jnp.zeros((2, 4), jnp.int32)
    gg = greedy_generate(params, prompt, 5, **CFG)
    # a tiny nucleus keeps only the argmax token -> greedy
    p_tiny = generate(params, prompt, 5, **CFG, temperature=1.0,
                      top_p=1e-6, seed=5)
    np.testing.assert_array_equal(np.asarray(p_tiny), np.asarray(gg))
    # top_p=0 disables the filter: identical stream to unfiltered sampling
    s_plain = generate(params, prompt, 5, **CFG, temperature=1.5, seed=9)
    s_full = generate(params, prompt, 5, **CFG, temperature=1.5,
                      top_p=0.0, seed=9)
    np.testing.assert_array_equal(np.asarray(s_plain), np.asarray(s_full))
    # reproducible per seed; a mid-size nucleus still varies across seeds
    n1 = generate(params, prompt, 8, **CFG, temperature=2.0, top_p=0.9,
                  seed=11)
    n1b = generate(params, prompt, 8, **CFG, temperature=2.0, top_p=0.9,
                   seed=11)
    n2 = generate(params, prompt, 8, **CFG, temperature=2.0, top_p=0.9,
                  seed=12)
    np.testing.assert_array_equal(np.asarray(n1), np.asarray(n1b))
    assert (np.asarray(n1) != np.asarray(n2)).any()


def test_generate_reuses_compiled_program():
    """Repeated generate() calls with identical shapes/config must hit the
    lru-cached jitted program instead of re-tracing per call (the serving
    loop would otherwise recompile on every request)."""
    from pytorch_distributed_tpu.models import generate as gen_mod

    params = _trained_params(seed=4)
    prompt = jnp.zeros((1, 4), jnp.int32)
    gen_mod._make_run.cache_clear()
    greedy_generate(params, prompt, 3, **CFG)
    info1 = gen_mod._make_run.cache_info()
    out2 = greedy_generate(params, prompt, 3, **CFG)
    info2 = gen_mod._make_run.cache_info()
    assert info2.misses == info1.misses == 1
    assert info2.hits == info1.hits + 1
    # a different sampling config is a different program, not a stale hit
    gen_mod.generate(params, prompt, 3, **CFG, temperature=1.0, top_k=2,
                     seed=1)
    assert gen_mod._make_run.cache_info().misses == 2
    assert out2.shape == (1, 3)


def test_topk_nucleus_fast_path_matches_full_sort():
    """With top_k >= vocab the k-truncation is a no-op, so the top-k fast
    nucleus path (cutoff from the sorted k-vector) must produce the same
    stream as the full-vocab-argsort nucleus path."""
    from pytorch_distributed_tpu.models.generate import generate

    params = _trained_params(seed=6)
    prompt = jnp.zeros((2, 4), jnp.int32)
    for seed in (0, 3, 17):
        slow = generate(params, prompt, 8, **CFG, temperature=1.3,
                        top_p=0.8, seed=seed)
        fast = generate(params, prompt, 8, **CFG, temperature=1.3,
                        top_k=CFG["vocab_size"], top_p=0.8, seed=seed)
        np.testing.assert_array_equal(np.asarray(slow), np.asarray(fast))


def test_tp_generate_matches_single_device():
    """Model-parallel decode (Megatron-sharded params over a 4-way model
    axis) must produce the same greedy stream as single-device generate."""
    from pytorch_distributed_tpu.models.generate import tp_generate
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh

    params = _trained_params(seed=8)
    rng = np.random.default_rng(8)
    prompt = jnp.asarray(rng.integers(0, 64, size=(2, 5)).astype(np.int32))

    want = greedy_generate(params, prompt, 6, **CFG)
    mesh = build_mesh(MeshSpec(("model",), (4,)), jax.devices()[:4])
    got = tp_generate(params, prompt, 6, mesh=mesh, **CFG)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_flash_prefill_matches_dense_prefill():
    """Decode-mode prompt prefill through the fused kernel (interpret on
    CPU) must produce the same logits and the same cache as the masked
    dense-over-cache path."""
    params = _trained_params(seed=9)
    rng = np.random.default_rng(9)
    P, EXTRA = 256, 4
    tokens = jnp.asarray(rng.integers(0, 64, size=(2, P)).astype(np.int32))

    outs, caches = [], []
    for fp in (False, True):
        model = TransformerLM(**CFG, decode=True, max_len=P + EXTRA,
                              flash_prefill=fp)
        cache = model.init(jax.random.PRNGKey(0), tokens)["cache"]
        out, mut = model.apply({"params": params, "cache": cache},
                               tokens, mutable=["cache"])
        outs.append(np.asarray(out))
        caches.append(mut["cache"])
    np.testing.assert_allclose(outs[1], outs[0], rtol=2e-4, atol=2e-4)
    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_leaves_with_path(caches[0]),
            jax.tree_util.tree_leaves_with_path(caches[1])):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-4, atol=2e-4,
                                   err_msg=jax.tree_util.keystr(pa))
    # incremental steps after a flash prefill continue correctly
    model = TransformerLM(**CFG, decode=True, max_len=P + EXTRA,
                          flash_prefill=True)
    ref = TransformerLM(**CFG, decode=True, max_len=P + EXTRA)
    nxt = jnp.asarray(rng.integers(0, 64, size=(2, 1)).astype(np.int32))
    o1, _ = model.apply({"params": params, "cache": caches[1]}, nxt,
                        mutable=["cache"])
    o0, _ = ref.apply({"params": params, "cache": caches[0]}, nxt,
                      mutable=["cache"])
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o0),
                               rtol=2e-4, atol=2e-4)
