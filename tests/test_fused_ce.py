"""Fused tied-head+CE (ops/fused_ce.py): numerics must equal the unfused
logits-materializing path — op-level (values + all grads) and step-level
(one LM optimizer step)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.models.transformer import TransformerLM
from pytorch_distributed_tpu.ops.fused_ce import fused_ce_sums
from pytorch_distributed_tpu.parallel import data_parallel_mesh
from pytorch_distributed_tpu.parallel.tp import replicated_like
from pytorch_distributed_tpu.train.lm import make_lm_train_step
from pytorch_distributed_tpu.train.optim import sgd_init
from pytorch_distributed_tpu.train.state import TrainState

N, D, V = 24, 16, 50


def _naive_sums(h, e, t, w):
    logits = (h.astype(jnp.float32) @ e.astype(jnp.float32).T)
    logz = jax.nn.logsumexp(logits, axis=-1)
    true_logit = jnp.take_along_axis(logits, t[:, None], axis=-1)[:, 0]
    loss = jnp.sum((logz - true_logit) * w)
    correct = jnp.sum(
        (jnp.argmax(logits, axis=-1) == t).astype(jnp.float32) * w)
    return loss, correct


def _op_inputs(seed=0, n=N, d=D, v=V, hit_frac=0.25):
    """Random op-level inputs; a fraction of targets is set to the argmax
    row so correct_sum is exercised nonzero."""
    rng = np.random.default_rng(seed)
    h = jnp.asarray(rng.normal(0, 1, size=(n, d)), jnp.float32)
    e = jnp.asarray(rng.normal(0, 1, size=(v, d)), jnp.float32)
    t = np.asarray(rng.integers(0, v, size=(n,)), np.int32)
    am = np.asarray(jnp.argmax(h @ e.T, axis=-1))
    hits = rng.random(n) < hit_frac
    t[hits] = am[hits]
    w = jnp.asarray(rng.uniform(0.5, 1.5, size=(n,)), jnp.float32)
    return h, e, jnp.asarray(t), w


@pytest.mark.parametrize("chunks", [1, 2, 4, 8])
def test_fused_ce_matches_naive(chunks):
    rng = np.random.default_rng(0)
    h = jnp.asarray(rng.normal(0, 1, size=(N, D)), jnp.float32)
    e = jnp.asarray(rng.normal(0, 1, size=(V, D)), jnp.float32)
    t = jnp.asarray(rng.integers(0, V, size=(N,)), jnp.int32)
    w = jnp.asarray(rng.uniform(0.5, 1.5, size=(N,)), jnp.float32)

    # value_and_grad needs a scalar; differentiate the loss output only
    fused_loss = lambda h, e: fused_ce_sums(h, e, t, w, chunks)[0]  # noqa: E731
    naive_loss = lambda h, e: _naive_sums(h, e, t, w)[0]  # noqa: E731
    lv_f, (gh_f, ge_f) = jax.value_and_grad(fused_loss, argnums=(0, 1))(h, e)
    lv_n, (gh_n, ge_n) = jax.value_and_grad(naive_loss, argnums=(0, 1))(h, e)
    np.testing.assert_allclose(float(lv_f), float(lv_n), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(gh_f), np.asarray(gh_n),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(ge_f), np.asarray(ge_n),
                               rtol=1e-5, atol=1e-6)
    # correct_sum (non-diff output) also matches
    _, cf = fused_ce_sums(h, e, t, w, chunks)
    _, cn = _naive_sums(h, e, t, w)
    np.testing.assert_allclose(float(cf), float(cn))


def test_fused_ce_pads_indivisible_rows():
    """N not divisible by num_chunks: weight-0 padding keeps values and
    grads exact (the LM's N = B*(L-1) is rarely chunk-aligned)."""
    rng = np.random.default_rng(3)
    h = jnp.asarray(rng.normal(0, 1, size=(6, 4)), jnp.float32)
    e = jnp.asarray(rng.normal(0, 1, size=(5, 4)), jnp.float32)
    t = jnp.asarray(rng.integers(0, 5, size=(6,)), jnp.int32)
    w = jnp.ones((6,), jnp.float32)
    fused = lambda h, e: fused_ce_sums(h, e, t, w, 4)[0]  # noqa: E731
    naive = lambda h, e: _naive_sums(h, e, t, w)[0]  # noqa: E731
    lv_f, g_f = jax.value_and_grad(fused, argnums=(0, 1))(h, e)
    lv_n, g_n = jax.value_and_grad(naive, argnums=(0, 1))(h, e)
    np.testing.assert_allclose(float(lv_f), float(lv_n), rtol=1e-6)
    for a, b in zip(g_f, g_n):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_tp_step_with_fused_ce_matches_replicated():
    """fused-CE composes with Megatron TP shardings under GSPMD: the
    chunked scan's per-block logits shard on the vocab axis and XLA
    inserts the logsumexp/softmax collectives — one TP step must equal
    the replicated fused step."""
    import jax.numpy as jnp

    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
    from pytorch_distributed_tpu.parallel.tp import shard_state, tp_specs

    cfg = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=2)
    model = TransformerLM(**cfg)
    tokens = jnp.asarray(
        np.random.default_rng(2).integers(0, 64, size=(8, 17)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens[:1, :8])["params"]

    def run(mesh, specs):
        fresh = jax.tree_util.tree_map(jnp.array, params)
        state = shard_state(
            TrainState.create({"params": fresh}, sgd_init(fresh)),
            specs, mesh)
        step = make_lm_train_step(model, mesh, specs, fused_ce_chunks=4)
        return step(state, tokens, jnp.float32(0.05))

    mesh_tp = build_mesh(MeshSpec(("data", "model"), (2, 4)),
                         jax.devices()[:8])
    mesh_dp = build_mesh(MeshSpec(("data",), (8,)), jax.devices()[:8])
    s_tp, m_tp = run(mesh_tp, tp_specs(params))
    s_dp, m_dp = run(mesh_dp, replicated_like(params))
    np.testing.assert_allclose(float(m_tp["loss"]), float(m_dp["loss"]),
                               rtol=1e-4)
    np.testing.assert_allclose(float(m_tp["acc"]), float(m_dp["acc"]),
                               rtol=1e-4, atol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(s_tp.params),
                    jax.tree_util.tree_leaves(s_dp.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_fused_ce_weights_grad_matches_naive():
    """The loss-path ``weights`` cotangent (ADVICE r5: _bwd used to return
    None): grad w.r.t. the per-row weights must match the naive
    logits-materializing autodiff — (logz − true_logit) per row."""
    h, e, t, w = _op_inputs(seed=7)
    gw_f = jax.grad(lambda w: fused_ce_sums(h, e, t, w, 4)[0])(w)
    gw_n = jax.grad(lambda w: _naive_sums(h, e, t, w)[0])(w)
    assert float(jnp.max(jnp.abs(gw_f))) > 0.0
    np.testing.assert_allclose(np.asarray(gw_f), np.asarray(gw_n),
                               rtol=1e-5, atol=1e-6)


def test_dp_mode_matches_naive_op_level():
    """fused_ce_sums_dp on an 8-way data mesh: values, correct_sum and all
    three grads (h, e, w) ≡ the naive path; the backward's dE accumulator
    is a [V/8, D] vocab-row shard per device (the replicated-[V,D] fix)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pytorch_distributed_tpu.ops.fused_ce import fused_ce_sums_dp
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(("data",), (8,)), jax.devices()[:8])
    h, e, t, w = _op_inputs(seed=11, v=64)
    hs = jax.device_put(h, NamedSharding(mesh, P("data", None)))
    ts = jax.device_put(t, NamedSharding(mesh, P("data")))
    ws = jax.device_put(w, NamedSharding(mesh, P("data")))

    @jax.jit
    def vals_and_grads(h, e, w):
        def f(h, e, w):
            return fused_ce_sums_dp(h, e, ts, w, 3, mesh)[0]

        return jax.value_and_grad(f, argnums=(0, 1, 2))(h, e, w)

    lv, grads = vals_and_grads(hs, e, ws)
    ln, cn = _naive_sums(h, e, t, w)
    gn = jax.grad(lambda h, e, w: _naive_sums(h, e, t, w)[0],
                  argnums=(0, 1, 2))(h, e, w)
    np.testing.assert_allclose(float(lv), float(ln), rtol=1e-6)
    for got, want, name in zip(grads, gn, ("h", "e", "w")):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-5, err_msg=name)
    cd = fused_ce_sums_dp(hs, e, ts, ws, 3, mesh)[1]
    assert float(cn) > 0.0  # the hit fraction keeps this exercised
    np.testing.assert_allclose(float(cd), float(cn), rtol=1e-6)


def test_tp_mode_matches_replicated_with_vocab_sharded_embedding():
    """fused_ce_sums_tp under shard_map with the parallel/tp.py
    vocab-sharded embedding (P('model', None)) ≡ the replicated
    fused_ce_sums: values, correct_sum, and all grads — with e entering
    (and its cotangent leaving) vocab-sharded, never replicated."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pytorch_distributed_tpu.ops.fused_ce import fused_ce_sums_tp
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(("data", "model"), (2, 4)),
                      jax.devices()[:8])
    h, e, t, w = _op_inputs(seed=13, v=64)
    es = jax.device_put(e, NamedSharding(mesh, P("model", None)))
    hs = jax.device_put(h, NamedSharding(mesh, P("data", None)))

    @jax.jit
    def vals_and_grads(h, e, w):
        def f(h, e, w):
            return fused_ce_sums_tp(h, e, t, w, 3, mesh)[0]

        return jax.value_and_grad(f, argnums=(0, 1, 2))(h, e, w)

    lv, grads = vals_and_grads(hs, es, w)
    lr_, cr = fused_ce_sums(h, e, t, w, 3)
    gr = jax.grad(lambda h, e, w: fused_ce_sums(h, e, t, w, 3)[0],
                  argnums=(0, 1, 2))(h, e, w)
    np.testing.assert_allclose(float(lv), float(lr_), rtol=1e-6)
    for got, want, name in zip(grads, gr, ("h", "e", "w")):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-5, err_msg=name)
    # e's cotangent must come back vocab-sharded (no dE replication)
    ge_spec = grads[1].sharding.spec
    assert ge_spec[0] == "model", ge_spec
    ct = fused_ce_sums_tp(hs, es, t, w, 3, mesh)[1]
    assert float(cr) > 0.0
    np.testing.assert_allclose(float(ct), float(cr), rtol=1e-6)


def test_dp_mode_step_matches_replicated_and_unfused():
    """Step-level DP parity on the 8-way data mesh: fused_ce_mode='dp' ≡
    'replicated' ≡ unfused — loss/acc and the updated params (i.e. the
    gradients) agree to fp-reassociation tolerance."""
    cfg = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=2)
    model = TransformerLM(**cfg)
    mesh = data_parallel_mesh()
    tokens = jnp.asarray(
        np.random.default_rng(5).integers(0, 64, size=(8, 17)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens[:1, :8])["params"]

    def one_step(chunks, mode):
        state = TrainState.create(
            {"params": jax.tree_util.tree_map(jnp.copy, params)},
            sgd_init(params))
        step = make_lm_train_step(
            model, mesh, replicated_like(params), fused_ce_chunks=chunks,
            fused_ce_mode=mode)
        return step(state, tokens, jnp.float32(0.1))

    s_dp, m_dp = one_step(4, "dp")
    s_rep, m_rep = one_step(4, "replicated")
    s_un, m_un = one_step(0, "auto")
    for (s, m), tag in (((s_rep, m_rep), "dp-vs-replicated"),
                        ((s_un, m_un), "dp-vs-unfused")):
        np.testing.assert_allclose(float(m_dp["loss"]), float(m["loss"]),
                                   rtol=1e-5, err_msg=tag)
        np.testing.assert_allclose(float(m_dp["acc"]), float(m["acc"]),
                                   rtol=1e-5, atol=1e-5, err_msg=tag)
        want = dict(jax.tree_util.tree_leaves_with_path(s.params))
        for path, v in jax.tree_util.tree_leaves_with_path(s_dp.params):
            np.testing.assert_allclose(
                np.asarray(v), np.asarray(want[path]), rtol=1e-4,
                atol=1e-5, err_msg=f"{tag}:{jax.tree_util.keystr(path)}")


def test_fused_ce_mode_validation():
    """Explicit mis-paired modes fail loudly at step-build time."""
    from pytorch_distributed_tpu.train.lm import resolve_fused_ce_mode

    from jax.sharding import PartitionSpec as P

    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
    from pytorch_distributed_tpu.parallel.tp import tp_specs

    cfg = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=2)
    model = TransformerLM(**cfg)
    tokens0 = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens0)["params"]
    mesh_dp = build_mesh(MeshSpec(("data",), (8,)), jax.devices()[:8])
    mesh_tp = build_mesh(MeshSpec(("data", "model"), (2, 4)),
                         jax.devices()[:8])
    rep = replicated_like(params)
    # tp on a replicated spec → loud error
    with pytest.raises(ValueError, match="fused_ce_mode='tp'"):
        resolve_fused_ce_mode("tp", rep, mesh_dp, 64)
    # dp with a vocab the data axis doesn't divide → loud error
    with pytest.raises(ValueError, match="fused_ce_mode='dp'"):
        resolve_fused_ce_mode("dp", rep, mesh_dp, 65)
    # auto: indivisible vocab falls back to replicated, never crashes
    assert resolve_fused_ce_mode("auto", rep, mesh_dp, 65)[0] == "replicated"
    assert resolve_fused_ce_mode("auto", rep, mesh_dp, 64)[0] == "dp"
    mode, axis = resolve_fused_ce_mode(
        "auto", tp_specs(params), mesh_tp, 64)
    assert (mode, axis) == ("tp", "model")
    with pytest.raises(ValueError, match="auto|replicated|dp|tp"):
        resolve_fused_ce_mode("bogus", rep, mesh_dp, 64)


@pytest.mark.parametrize("mode", ["replicated", "dp"])
def test_lm_step_fused_equals_unfused_bf16(mode):
    """bf16 variant of the fused-vs-unfused step parity (ADVICE r5): the
    fused path casts ln_f hidden + embedding to bf16 before the chunked
    matmul, exactly like the unfused head's embed-dtype cast — pinned here
    at loose bf16 tolerance rather than asserted by docstring alone."""
    cfg = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
               dtype=jnp.bfloat16)
    model = TransformerLM(**cfg)
    mesh = data_parallel_mesh()
    tokens = jnp.asarray(
        np.random.default_rng(9).integers(0, 64, size=(8, 17)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens[:1, :8])["params"]

    def one_step(chunks, mode):
        state = TrainState.create(
            {"params": jax.tree_util.tree_map(jnp.copy, params)},
            sgd_init(params))
        step = make_lm_train_step(
            model, mesh, replicated_like(params), fused_ce_chunks=chunks,
            fused_ce_mode=mode)
        return step(state, tokens, jnp.float32(0.1))

    s_f, m_f = one_step(4, mode)
    s_n, m_n = one_step(0, "auto")
    # bf16 has ~3 decimal digits: fused and unfused heads round the same
    # operands through different summation orders.
    np.testing.assert_allclose(float(m_f["loss"]), float(m_n["loss"]),
                               rtol=2e-2)
    # acc is percent over 128 tokens: allow a single bf16 argmax tie-flip
    np.testing.assert_allclose(float(m_f["acc"]), float(m_n["acc"]),
                               rtol=2e-2, atol=1.0)
    want = dict(jax.tree_util.tree_leaves_with_path(s_n.params))
    for path, v in jax.tree_util.tree_leaves_with_path(s_f.params):
        np.testing.assert_allclose(
            np.asarray(v, jnp.float32), np.asarray(want[path], jnp.float32),
            rtol=2e-2, atol=2e-3, err_msg=jax.tree_util.keystr(path))


def test_lm_step_fused_equals_unfused():
    """One full LM optimizer step, fused_ce_chunks=4 vs 0 (f32): metrics
    and updated params must agree to fp tolerance."""
    cfg = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=2)
    model = TransformerLM(**cfg)
    mesh = data_parallel_mesh()
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, 64, size=(8, 17)), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens[:1, :8])
    params = variables["params"]

    def one_step(chunks):
        state = TrainState.create(
            {"params": jax.tree_util.tree_map(jnp.copy, params)},
            sgd_init(params))
        step = make_lm_train_step(
            model, mesh, replicated_like(params), fused_ce_chunks=chunks)
        return step(state, tokens, jnp.float32(0.1))

    s_f, m_f = one_step(4)
    s_n, m_n = one_step(0)
    np.testing.assert_allclose(float(m_f["loss"]), float(m_n["loss"]),
                               rtol=1e-5)
    np.testing.assert_allclose(float(m_f["acc"]), float(m_n["acc"]),
                               rtol=1e-5, atol=1e-5)
    got = jax.tree_util.tree_leaves_with_path(s_f.params)
    want = dict(jax.tree_util.tree_leaves_with_path(s_n.params))
    for path, v in got:
        np.testing.assert_allclose(
            np.asarray(v), np.asarray(want[path]), rtol=1e-4, atol=1e-5,
            err_msg=jax.tree_util.keystr(path))
