"""Fused tied-head+CE (ops/fused_ce.py): numerics must equal the unfused
logits-materializing path — op-level (values + all grads) and step-level
(one LM optimizer step)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.models.transformer import TransformerLM
from pytorch_distributed_tpu.ops.fused_ce import fused_ce_sums
from pytorch_distributed_tpu.parallel import data_parallel_mesh
from pytorch_distributed_tpu.parallel.tp import replicated_like
from pytorch_distributed_tpu.train.lm import make_lm_train_step
from pytorch_distributed_tpu.train.optim import sgd_init
from pytorch_distributed_tpu.train.state import TrainState

N, D, V = 24, 16, 50


def _naive_sums(h, e, t, w):
    logits = (h.astype(jnp.float32) @ e.astype(jnp.float32).T)
    logz = jax.nn.logsumexp(logits, axis=-1)
    true_logit = jnp.take_along_axis(logits, t[:, None], axis=-1)[:, 0]
    loss = jnp.sum((logz - true_logit) * w)
    correct = jnp.sum(
        (jnp.argmax(logits, axis=-1) == t).astype(jnp.float32) * w)
    return loss, correct


@pytest.mark.parametrize("chunks", [1, 2, 4, 8])
def test_fused_ce_matches_naive(chunks):
    rng = np.random.default_rng(0)
    h = jnp.asarray(rng.normal(0, 1, size=(N, D)), jnp.float32)
    e = jnp.asarray(rng.normal(0, 1, size=(V, D)), jnp.float32)
    t = jnp.asarray(rng.integers(0, V, size=(N,)), jnp.int32)
    w = jnp.asarray(rng.uniform(0.5, 1.5, size=(N,)), jnp.float32)

    # value_and_grad needs a scalar; differentiate the loss output only
    fused_loss = lambda h, e: fused_ce_sums(h, e, t, w, chunks)[0]  # noqa: E731
    naive_loss = lambda h, e: _naive_sums(h, e, t, w)[0]  # noqa: E731
    lv_f, (gh_f, ge_f) = jax.value_and_grad(fused_loss, argnums=(0, 1))(h, e)
    lv_n, (gh_n, ge_n) = jax.value_and_grad(naive_loss, argnums=(0, 1))(h, e)
    np.testing.assert_allclose(float(lv_f), float(lv_n), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(gh_f), np.asarray(gh_n),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(ge_f), np.asarray(ge_n),
                               rtol=1e-5, atol=1e-6)
    # correct_sum (non-diff output) also matches
    _, cf = fused_ce_sums(h, e, t, w, chunks)
    _, cn = _naive_sums(h, e, t, w)
    np.testing.assert_allclose(float(cf), float(cn))


def test_fused_ce_pads_indivisible_rows():
    """N not divisible by num_chunks: weight-0 padding keeps values and
    grads exact (the LM's N = B*(L-1) is rarely chunk-aligned)."""
    rng = np.random.default_rng(3)
    h = jnp.asarray(rng.normal(0, 1, size=(6, 4)), jnp.float32)
    e = jnp.asarray(rng.normal(0, 1, size=(5, 4)), jnp.float32)
    t = jnp.asarray(rng.integers(0, 5, size=(6,)), jnp.int32)
    w = jnp.ones((6,), jnp.float32)
    fused = lambda h, e: fused_ce_sums(h, e, t, w, 4)[0]  # noqa: E731
    naive = lambda h, e: _naive_sums(h, e, t, w)[0]  # noqa: E731
    lv_f, g_f = jax.value_and_grad(fused, argnums=(0, 1))(h, e)
    lv_n, g_n = jax.value_and_grad(naive, argnums=(0, 1))(h, e)
    np.testing.assert_allclose(float(lv_f), float(lv_n), rtol=1e-6)
    for a, b in zip(g_f, g_n):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_tp_step_with_fused_ce_matches_replicated():
    """fused-CE composes with Megatron TP shardings under GSPMD: the
    chunked scan's per-block logits shard on the vocab axis and XLA
    inserts the logsumexp/softmax collectives — one TP step must equal
    the replicated fused step."""
    import jax.numpy as jnp

    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
    from pytorch_distributed_tpu.parallel.tp import shard_state, tp_specs

    cfg = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=2)
    model = TransformerLM(**cfg)
    tokens = jnp.asarray(
        np.random.default_rng(2).integers(0, 64, size=(8, 17)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens[:1, :8])["params"]

    def run(mesh, specs):
        fresh = jax.tree_util.tree_map(jnp.array, params)
        state = shard_state(
            TrainState.create({"params": fresh}, sgd_init(fresh)),
            specs, mesh)
        step = make_lm_train_step(model, mesh, specs, fused_ce_chunks=4)
        return step(state, tokens, jnp.float32(0.05))

    mesh_tp = build_mesh(MeshSpec(("data", "model"), (2, 4)),
                         jax.devices()[:8])
    mesh_dp = build_mesh(MeshSpec(("data",), (8,)), jax.devices()[:8])
    s_tp, m_tp = run(mesh_tp, tp_specs(params))
    s_dp, m_dp = run(mesh_dp, replicated_like(params))
    np.testing.assert_allclose(float(m_tp["loss"]), float(m_dp["loss"]),
                               rtol=1e-4)
    np.testing.assert_allclose(float(m_tp["acc"]), float(m_dp["acc"]),
                               rtol=1e-4, atol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(s_tp.params),
                    jax.tree_util.tree_leaves(s_dp.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_lm_step_fused_equals_unfused():
    """One full LM optimizer step, fused_ce_chunks=4 vs 0 (f32): metrics
    and updated params must agree to fp tolerance."""
    cfg = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=2)
    model = TransformerLM(**cfg)
    mesh = data_parallel_mesh()
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, 64, size=(8, 17)), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens[:1, :8])
    params = variables["params"]

    def one_step(chunks):
        state = TrainState.create(
            {"params": jax.tree_util.tree_map(jnp.copy, params)},
            sgd_init(params))
        step = make_lm_train_step(
            model, mesh, replicated_like(params), fused_ce_chunks=chunks)
        return step(state, tokens, jnp.float32(0.1))

    s_f, m_f = one_step(4)
    s_n, m_n = one_step(0)
    np.testing.assert_allclose(float(m_f["loss"]), float(m_n["loss"]),
                               rtol=1e-5)
    np.testing.assert_allclose(float(m_f["acc"]), float(m_n["acc"]),
                               rtol=1e-5, atol=1e-5)
    got = jax.tree_util.tree_leaves_with_path(s_f.params)
    want = dict(jax.tree_util.tree_leaves_with_path(s_n.params))
    for path, v in got:
        np.testing.assert_allclose(
            np.asarray(v), np.asarray(want[path]), rtol=1e-4, atol=1e-5,
            err_msg=jax.tree_util.keystr(path))
