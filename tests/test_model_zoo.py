"""Every registered image arch initializes, runs forward, and (for a sample
incl. a dropout model) takes a train step on the simulated mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu import models
from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
from pytorch_distributed_tpu.train.optim import sgd_init
from pytorch_distributed_tpu.train.state import TrainState
from pytorch_distributed_tpu.train.steps import make_train_step

EXPECTED = {
    "alexnet", "vgg11", "vgg13", "vgg16", "vgg19",
    "vgg11_bn", "vgg13_bn", "vgg16_bn", "vgg19_bn",
    "densenet121", "densenet161", "densenet169", "densenet201",
    "mobilenet_v2",
    "resnet18", "resnet34", "resnet50", "resnet101", "resnet152",
    "wide_resnet50_2", "wide_resnet101_2",
    "resnext50_32x4d", "resnext101_32x8d",
    "squeezenet1_0", "squeezenet1_1",
    "shufflenet_v2_x0_5", "shufflenet_v2_x1_0",
    "shufflenet_v2_x1_5", "shufflenet_v2_x2_0",
    "mnasnet0_5", "mnasnet0_75", "mnasnet1_0", "mnasnet1_3",
    "googlenet", "inception_v3",
}


def test_registry_contains_expected_families():
    assert EXPECTED <= set(models.model_names())


# Keep per-arch cost low: one light representative per family at tiny size.
FWD_ARCHS = ["alexnet", "vgg11_bn", "densenet121", "mobilenet_v2",
             "resnet34", "squeezenet1_1", "shufflenet_v2_x0_5",
             "mnasnet0_5"]


@pytest.mark.parametrize("arch", FWD_ARCHS)
def test_forward_shapes(arch):
    model = models.create_model(arch, num_classes=7)
    size = 64 if arch == "alexnet" else 32  # alexnet's 11x11/s4 stem needs room
    x = jnp.zeros((2, size, size, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 7)
    assert out.dtype == jnp.float32


def test_googlenet_forward_and_aux():
    """96px keeps the test cheap (aux adaptive-pool keeps param shapes
    size-independent); aux logits are returned only under capture_aux."""
    model = models.create_model("googlenet", num_classes=5)
    x = jnp.zeros((2, 96, 96, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 5)

    aux_model = models.create_model("googlenet", num_classes=5, aux_logits=True)
    variables = aux_model.init(jax.random.PRNGKey(0), x, train=False)
    logits, (a1, a2) = aux_model.apply(
        variables, x, train=False, capture_aux=True,
        rngs={"dropout": jax.random.PRNGKey(1)},
    )
    assert logits.shape == a1.shape == a2.shape == (2, 5)


def test_inception_v3_forward():
    model = models.create_model("inception_v3", num_classes=5)
    x = jnp.zeros((1, 96, 96, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (1, 5)


def test_inception_v3_aux_small_input_and_stats_tree():
    """Aux head must init at sub-299 sizes (clamped pool window) and the
    gated-out aux compute must not change the batch_stats tree structure
    across a mutable train-mode apply."""
    model = models.create_model("inception_v3", num_classes=5, aux_logits=True)
    x = jnp.zeros((1, 96, 96, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    logits, aux = model.apply(
        variables, x, train=False, capture_aux=True,
        rngs={"dropout": jax.random.PRNGKey(1)},
    )
    assert logits.shape == aux.shape == (1, 5)
    _, mutated = model.apply(
        variables, x, train=True, mutable=["batch_stats"],
        rngs={"dropout": jax.random.PRNGKey(1)},
    )
    assert (
        jax.tree_util.tree_structure(mutated["batch_stats"])
        == jax.tree_util.tree_structure(variables["batch_stats"])
    )


def test_dropout_arch_trains():
    """AlexNet has dropout: the train step must thread a dropout rng."""
    mesh = build_mesh(MeshSpec(("data",), (8,)), jax.devices()[:8])
    model = models.create_model("alexnet", num_classes=4)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)),
                           train=False)
    state = TrainState.create(variables, sgd_init(variables["params"]))
    step = make_train_step(model, mesh, seed=3)
    rng = np.random.default_rng(0)
    batch = {
        "images": rng.normal(size=(16, 64, 64, 3)).astype(np.float32),
        "labels": rng.integers(0, 4, size=16).astype(np.int32),
        "weights": np.ones(16, np.float32),
    }
    s1, m1 = step(state, batch, jnp.float32(0.01))
    assert np.isfinite(float(m1["loss"]))
    s2, m2 = step(s1, batch, jnp.float32(0.01))
    assert np.isfinite(float(m2["loss"]))


def test_vgg_trains_through_explicit_collectives():
    mesh = build_mesh(MeshSpec(("data",), (8,)), jax.devices()[:8])
    model = models.create_model("vgg11", num_classes=4)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)),
                           train=False)
    state = TrainState.create(variables, sgd_init(variables["params"]))
    step = make_train_step(model, mesh, explicit_collectives=True, seed=1)
    rng = np.random.default_rng(1)
    batch = {
        "images": rng.normal(size=(16, 32, 32, 3)).astype(np.float32),
        "labels": rng.integers(0, 4, size=16).astype(np.int32),
        "weights": np.ones(16, np.float32),
    }
    _, m = step(state, batch, jnp.float32(0.01))
    assert np.isfinite(float(m["loss"]))


def test_space_to_depth_stem_equivalence():
    """The packed stem must be numerically identical to the conv7 stem on
    the SAME parameters (both read conv_init/kernel (7,7,3,64))."""
    m_std = models.create_model("resnet50", num_classes=6)
    m_s2d = models.create_model("resnet50", num_classes=6,
                                stem="space_to_depth")
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(2, 64, 64, 3)).astype(np.float32)
    )
    variables = m_std.init(jax.random.PRNGKey(0), x, train=False)
    v2 = m_s2d.init(jax.random.PRNGKey(0), x, train=False)
    assert (
        jax.tree_util.tree_structure(v2) ==
        jax.tree_util.tree_structure(variables)
    )
    out_std = m_std.apply(variables, x, train=False)
    out_s2d = m_s2d.apply(variables, x, train=False)
    np.testing.assert_allclose(
        np.asarray(out_s2d), np.asarray(out_std), rtol=2e-4, atol=2e-5
    )


def test_adaptive_avg_pool_matches_torch():
    """Non-divisible sizes must follow torch AdaptiveAvgPool2d bin edges
    (regression: earlier fallback collapsed to a global mean)."""
    torch = pytest.importorskip("torch")
    from pytorch_distributed_tpu.models.simple import _adaptive_avg_pool

    rng = np.random.default_rng(0)
    for H, out in ((8, 7), (5, 7), (13, 6), (1, 7), (14, 7)):
        x = rng.normal(size=(2, H, H, 3)).astype(np.float32)
        want = (
            torch.nn.AdaptiveAvgPool2d(out)(
                torch.from_numpy(x.transpose(0, 3, 1, 2))
            ).numpy().transpose(0, 2, 3, 1)
        )
        got = np.asarray(_adaptive_avg_pool(jnp.asarray(x), out))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
