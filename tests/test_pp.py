"""Pipeline parallelism: pipelined stages ≡ sequential stage application."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
from pytorch_distributed_tpu.parallel.pp import pipeline_apply


def _mesh(p=4):
    return build_mesh(MeshSpec(("pipe",), (p,)), jax.devices()[:p])


def _stage_fn(params, x):
    # One affine+nonlinearity stage: x @ W + b through tanh.
    return jnp.tanh(x @ params["w"] + params["b"])


def _stacked_params(n_stages, d, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "w": jnp.asarray(rng.normal(size=(n_stages, d, d)).astype(np.float32) * 0.5),
        "b": jnp.asarray(rng.normal(size=(n_stages, 1, d)).astype(np.float32)),
    }


def _sequential(params, x, n_stages):
    for s in range(n_stages):
        x = _stage_fn(jax.tree_util.tree_map(lambda a: a[s], params), x)
    return x


@pytest.mark.parametrize("n_micro", [1, 2, 4, 8])
def test_pipeline_matches_sequential(n_micro):
    P_ = 4
    mesh = _mesh(P_)
    params = _stacked_params(P_, 8)
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(8, 16, 8)).astype(np.float32))
    want = _sequential(params, x, P_)
    got = pipeline_apply(_stage_fn, params, x, n_micro, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_gradients_match_sequential():
    P_ = 4
    mesh = _mesh(P_)
    params = _stacked_params(P_, 4)
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(4, 8, 4)).astype(np.float32))

    def loss_pp(params):
        return jnp.sum(pipeline_apply(_stage_fn, params, x, 4, mesh) ** 2)

    def loss_seq(params):
        return jnp.sum(_sequential(params, x, P_) ** 2)

    gp = jax.grad(loss_pp)(params)
    gs = jax.grad(loss_seq)(params)
    for a, b in zip(jax.tree_util.tree_leaves(gp), jax.tree_util.tree_leaves(gs)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_pipeline_rejects_indivisible_microbatches():
    mesh = _mesh(4)
    params = _stacked_params(4, 4)
    x = jnp.zeros((6, 8, 4))
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_apply(_stage_fn, params, x, 4, mesh)


# ---------------------------------------------------------------- LM family

def test_pipelined_lm_matches_sequential():
    """PipelinedTransformerLM (round-2: PP integrated into the LM family)
    must equal the stage-by-stage sequential application of its own params."""
    from jax.sharding import NamedSharding, PartitionSpec as P_
    from pytorch_distributed_tpu.models.pipeline_lm import PipelinedTransformerLM
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(("data", "pipe"), (2, 4)), jax.devices()[:8])
    model = PipelinedTransformerLM(
        vocab_size=64, d_model=32, n_heads=2, n_layers=4, n_stages=4,
        n_microbatches=2, mesh=mesh,
    )
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, 64, size=(4, 16)).astype(np.int32))
    with mesh:
        variables = model.init(jax.random.PRNGKey(0), tokens)
        logits = model.apply(variables, tokens)

        p = variables["params"]
        x = model._embed.apply({"params": p["embed"]}, tokens)
        for s in range(4):
            sp = jax.tree_util.tree_map(lambda a: a[s], p["stages"])
            x = model._stage.apply({"params": sp}, x)
        x = model._ln_f.apply({"params": p["ln_f"]}, x.astype(jnp.float32))
        want = model._embed.apply({"params": p["embed"]}, x,
                                  method=__import__("flax").linen.Embed.attend)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_pipelined_lm_trains_under_lm_step():
    """Full train step over the ("data","pipe") mesh through LMTrainer."""
    from pytorch_distributed_tpu.models.pipeline_lm import (
        PipelinedTransformerLM, pp_specs,
    )
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
    from pytorch_distributed_tpu.train.lm import LMTrainer, SyntheticTokenDataset

    mesh = build_mesh(MeshSpec(("data", "pipe"), (2, 4)), jax.devices()[:8])
    model = PipelinedTransformerLM(
        vocab_size=32, d_model=32, n_heads=2, n_layers=4, n_stages=4,
        n_microbatches=2, mesh=mesh,
    )
    tokens0 = jnp.zeros((2, 16), jnp.int32)
    specs = pp_specs(model.init(jax.random.PRNGKey(0), tokens0)["params"])
    ds = SyntheticTokenDataset(8, 16, 32, seed=0)
    with mesh:
        t = LMTrainer(model, mesh, ds, batch_size=8, lr=0.05,
                      param_specs=specs, eval_dataset=ds, eval_batches=1)
        loss = t.fit(12, print_freq=4)
    assert np.isfinite(loss)
