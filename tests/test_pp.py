"""Pipeline parallelism: pipelined stages ≡ sequential stage application."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
from pytorch_distributed_tpu.parallel.pp import pipeline_apply


def _mesh(p=4):
    return build_mesh(MeshSpec(("pipe",), (p,)), jax.devices()[:p])


def _stage_fn(params, x):
    # One affine+nonlinearity stage: x @ W + b through tanh.
    return jnp.tanh(x @ params["w"] + params["b"])


def _stacked_params(n_stages, d, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "w": jnp.asarray(rng.normal(size=(n_stages, d, d)).astype(np.float32) * 0.5),
        "b": jnp.asarray(rng.normal(size=(n_stages, 1, d)).astype(np.float32)),
    }


def _sequential(params, x, n_stages):
    for s in range(n_stages):
        x = _stage_fn(jax.tree_util.tree_map(lambda a: a[s], params), x)
    return x


@pytest.mark.parametrize("n_micro", [1, 2, 4, 8])
def test_pipeline_matches_sequential(n_micro):
    P_ = 4
    mesh = _mesh(P_)
    params = _stacked_params(P_, 8)
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(8, 16, 8)).astype(np.float32))
    want = _sequential(params, x, P_)
    got = pipeline_apply(_stage_fn, params, x, n_micro, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_gradients_match_sequential():
    P_ = 4
    mesh = _mesh(P_)
    params = _stacked_params(P_, 4)
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(4, 8, 4)).astype(np.float32))

    def loss_pp(params):
        return jnp.sum(pipeline_apply(_stage_fn, params, x, 4, mesh) ** 2)

    def loss_seq(params):
        return jnp.sum(_sequential(params, x, P_) ** 2)

    gp = jax.grad(loss_pp)(params)
    gs = jax.grad(loss_seq)(params)
    for a, b in zip(jax.tree_util.tree_leaves(gp), jax.tree_util.tree_leaves(gs)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_pipeline_rejects_indivisible_microbatches():
    mesh = _mesh(4)
    params = _stacked_params(4, 4)
    x = jnp.zeros((6, 8, 4))
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_apply(_stage_fn, params, x, 4, mesh)
