"""Checkpoint round-trip, best-copy, rank guard, and resume semantics."""

import os

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_tpu import models
from pytorch_distributed_tpu.train.checkpoint import (
    BEST_NAME,
    CHECKPOINT_NAME,
    load_checkpoint,
    save_checkpoint,
)
from pytorch_distributed_tpu.train.optim import sgd_init
from pytorch_distributed_tpu.train.state import TrainState


def _state(seed=0):
    model = models.create_model("resnet18", num_classes=10)
    variables = model.init(jax.random.PRNGKey(seed), jnp.zeros((1, 32, 32, 3)), train=False)
    return TrainState.create(variables, sgd_init(variables["params"]))


def _tree_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_round_trip(tmp_path):
    state = _state(seed=1)
    path = save_checkpoint(
        str(tmp_path), state, epoch=7, arch="resnet18", best_acc1=55.5, is_best=False
    )
    assert path and os.path.exists(path)
    template = _state(seed=2)  # different values, same structure
    restored, meta = load_checkpoint(path, template)
    ft = meta.pop("ft")
    assert meta == {"epoch": 7, "arch": "resnet18", "best_acc1": 55.5}
    # No ft record passed: defaults = epoch-boundary semantics.
    assert ft["step"] == 0 and ft["lr_scale"] == 1.0
    _tree_equal(restored.params, state.params)
    _tree_equal(restored.momentum, state.momentum)
    _tree_equal(restored.batch_stats, state.batch_stats)


def test_best_copy(tmp_path):
    state = _state()
    save_checkpoint(str(tmp_path), state, 0, "resnet18", 10.0, is_best=True)
    assert os.path.exists(tmp_path / BEST_NAME)
    # Non-best save must not touch model_best.
    best_mtime = os.path.getmtime(tmp_path / BEST_NAME)
    save_checkpoint(str(tmp_path), state, 1, "resnet18", 10.0, is_best=False)
    assert os.path.getmtime(tmp_path / BEST_NAME) == best_mtime


def test_rank_guard(tmp_path):
    state = _state()
    out = save_checkpoint(
        str(tmp_path), state, 0, "resnet18", 0.0, is_best=True, is_primary=False
    )
    assert out is None
    assert not os.path.exists(tmp_path / CHECKPOINT_NAME)


def test_no_partial_file_on_overwrite(tmp_path):
    state = _state()
    save_checkpoint(str(tmp_path), state, 0, "resnet18", 1.0, is_best=False)
    save_checkpoint(str(tmp_path), state, 1, "resnet18", 2.0, is_best=False)
    _, meta = load_checkpoint(str(tmp_path / CHECKPOINT_NAME), _state())
    assert meta["epoch"] == 1
    assert not os.path.exists(str(tmp_path / CHECKPOINT_NAME) + ".tmp")
