"""Step-time attribution + roofline plane (ISSUE 20).

The tentpole contract under test: with ``--step-attr`` every step's wall
time decomposes exactly —

    step_time == compute + exposed_comm + host_sync + data_wait + other

— reconciling to <= 0.5% of the p50 step time on real runs (image
GSPMD, image explicit-collectives, LM), because the recorder's windows
are *constructed* to close the identity (residual-clamped ``other``,
``block_until_ready`` fencing the device window, log_step accrual
aligned to the next step's dt).  Around the recorder: the roofline
classifier's labels on synthetic ledgers, the byte-split conservation
law, the planner profile round-trip, the jax-free CLI, the obs_report
``--diff`` composition fences, and the loader/heartbeat data-wait leg.
"""

import json
import os
import subprocess
import sys
import time
import types

import pytest

from pytorch_distributed_tpu.obs import stepattr
from pytorch_distributed_tpu.obs.metrics import MetricsLogger, read_metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ----------------------------------------------------------- recorder units

def test_identity_closes_by_construction():
    """Window sums never exceed the step: the residual lands in
    ``other`` (>= 0) and the recon error is exactly the overshoot."""
    sa = stepattr.StepAttr()
    with sa.data_wait():
        time.sleep(0.010)
    with sa.device():
        time.sleep(0.005)
    with sa.host_sync():
        time.sleep(0.002)
    f = sa.fields(0.030)
    total = sum(f[f"attr_{c}_ms"] for c in stepattr.COMPONENTS)
    # the identity: components sum to the step time (4dp rounding slack)
    assert total == pytest.approx(30.0, abs=0.01), f
    assert f["attr_data_wait_ms"] >= 10.0
    assert f["attr_other_ms"] >= 0.0
    assert f["attr_recon_err_ms"] == 0.0
    assert f["data_wait_share"] == pytest.approx(
        100.0 * f["attr_data_wait_ms"] / 30.0, abs=0.01)
    # windows reset per step: a second fields() on an idle step is clean
    f2 = sa.fields(0.001)
    assert f2["attr_device_ms"] == 0.0 and f2["attr_data_wait_ms"] == 0.0


def test_residual_clamp_measures_overshoot_only():
    """When the windows overshoot dt (mis-measured step), ``other``
    clamps to zero and recon_err records the overshoot — the identity
    still sums to step_time + recon_err, never silently under-reports."""
    sa = stepattr.StepAttr()
    with sa.device():
        time.sleep(0.010)
    f = sa.fields(0.004)  # dt shorter than the device window
    assert f["attr_other_ms"] == 0.0
    assert f["attr_recon_err_ms"] > 0.0
    total = sum(f[f"attr_{c}_ms"] for c in stepattr.COMPONENTS)
    assert total == pytest.approx(4.0 + f["attr_recon_err_ms"], abs=0.02)


def test_device_split_ledger_vs_timeline():
    """Without a timeline the exposed-comm estimate comes from the wire
    ledger (assumed overlap); a measured exposure overrides it and the
    summary records the provenance."""
    sa = stepattr.StepAttr(comm_bytes_per_step=1e9, link_bytes_per_s=1e11)
    # ledger estimate: 1e9 B / 1e11 B/s = 10 ms of comm; at the assumed
    # 0.6 overlap, 4 ms is exposed — capped by the device window
    compute, exposed, comm = sa._split_device(50.0)
    assert comm == pytest.approx(10.0)
    assert exposed == pytest.approx(4.0)
    assert compute == pytest.approx(46.0)
    # tiny device window: exposure cannot exceed it
    compute, exposed, comm = sa._split_device(2.0)
    assert exposed == pytest.approx(2.0) and compute == 0.0
    # a measured exposure fraction replaces the assumption
    sa.set_exposure(0.10, comm_frac=0.25, source="timeline")
    compute, exposed, comm = sa._split_device(40.0)
    assert exposed == pytest.approx(4.0)
    assert comm == pytest.approx(10.0)
    assert sa.exposure_source == "timeline"


def test_exposure_from_timeline():
    """The timeline bridge: analyze_steps-style per-step stats become the
    measured exposure/comm fractions for ``set_exposure``."""
    stat = types.SimpleNamespace(window_ns=100e6, exposed_ns=5e6,
                                 comm_ns=20e6)
    got = stepattr.exposure_from_timeline([stat, stat])
    assert got is not None
    assert got["exposed_frac"] == pytest.approx(0.05)
    assert got["comm_frac"] == pytest.approx(0.20)
    # no device streams ever opened -> nothing to measure
    assert stepattr.exposure_from_timeline([]) is None
    empty = types.SimpleNamespace(window_ns=0, exposed_ns=0, comm_ns=0)
    assert stepattr.exposure_from_timeline([empty]) is None


def test_split_step_bytes_conserves_the_cost_model():
    """The fwd/bwd/update byte split must conserve StepCost.bytes
    (24*params + activations) exactly — the roofline re-apportions, it
    never invents traffic."""
    params, act = 1e6, 3e7
    total = 24.0 * params + act
    split = stepattr.split_step_bytes(total, params)
    assert sum(split.values()) == pytest.approx(total)
    assert split["update"] == pytest.approx(12.0 * params)
    assert split["backward"] >= split["forward"]


# ------------------------------------------------------------------ roofline

def _mk_records(n=10, step_ms=100.0, comp=62.0, exp=8.0, sync=5.0,
                data=20.0, other=5.0, with_phases=True):
    recs = []
    if with_phases:
        prof = stepattr.phase_profile(
            {"forward": 1e9, "backward": 2e9, "update": 1e7},
            {"forward": 1e7, "backward": 2e7, "update": 1e8},
            comm_bytes=1e6, peak_flops=1e12, hbm_bw=1e11, link_bw=1e10)
        recs.append(dict(stepattr.phase_event_fields(prof),
                         ft_event="stepattr_phases", t=0.0, process=0))
    for i in range(n):
        recs.append({
            "step": i, "t": float(i), "process": 0, "kind": "step",
            "step_time": step_ms / 1e3,
            "attr_compute_ms": comp, "attr_exposed_comm_ms": exp,
            "attr_host_sync_ms": sync, "attr_data_wait_ms": data,
            "attr_other_ms": other, "attr_device_ms": comp + exp,
            "attr_comm_ms": max(exp, 10.0), "attr_recon_err_ms": 0.01,
            "data_wait_share": 100.0 * data / step_ms})
    return recs


def test_roofline_labels_on_synthetic_ledgers():
    """Every bound class pins: fwd/bwd clear the ridge (compute-bound),
    the optimizer streams state (hbm-bound), grad_sync is the wire
    (comm-bound), host components are host-bound; fix-first ranks by
    headroom."""
    recs = _mk_records()
    summ = stepattr.summarize(recs)
    assert summ is not None and summ["steps"] == 10
    assert summ["dominant"] == "compute"
    assert summ["recon_err_pct_p50"] <= 0.5
    ev = stepattr.phase_event(recs)
    assert ev is not None and isinstance(ev["phases"], list)
    roof = stepattr.roofline(summ, ev)
    assert roof["ridge_flops_per_byte"] == pytest.approx(10.0)
    labels = {p["phase"]: p["label"] for p in roof["phases"]}
    assert labels["forward"] == "compute-bound"
    assert labels["backward"] == "compute-bound"
    assert labels["update"] == "hbm-bound"
    assert labels["grad_sync"] == "comm-bound"
    assert labels["data_wait"] == "host-bound"
    assert labels["host_sync"] == "host-bound"
    # fix-first is sorted by headroom, descending
    head = [p["headroom_ms"] for p in roof["fix_first"]]
    assert head == sorted(head, reverse=True) and head[0] > 0


def test_phase_event_rides_the_metrics_logger(tmp_path):
    """The phases list must survive the logger's float-coercing flush:
    phase_event_fields JSON-encodes it, phase_event decodes it back."""
    prof = stepattr.phase_profile({"forward": 1e9}, {"forward": 1e7},
                                  peak_flops=1e12, hbm_bw=1e11)
    path = str(tmp_path / "m.jsonl")
    with MetricsLogger(path, flush_every=1) as log:
        log.log_event("stepattr_phases",
                      **stepattr.phase_event_fields(prof))
    back = stepattr.phase_event(read_metrics(path))
    assert back is not None
    assert back["phases"] == prof["phases"]
    assert back["peak_flops"] == prof["peak_flops"]


def test_attr_profile_round_trip(tmp_path):
    """summarize -> write_attr -> load_attr carries the planner-facing
    fields; a non-profile JSON is rejected loudly."""
    summ = stepattr.summarize(_mk_records())
    p = str(tmp_path / "attr.json")
    prof = stepattr.write_attr(p, summ)
    back = stepattr.load_attr(p)
    assert back["kind"] == "stepattr_profile"
    assert back["bottleneck"] == summ["dominant"]
    assert back["attr_source"] == p
    assert back["step_ms_p50"] == pytest.approx(prof["step_ms_p50"])
    bogus = str(tmp_path / "b.json")
    with open(bogus, "w") as f:
        json.dump({"overlap": 0.5}, f)
    with pytest.raises(ValueError):
        stepattr.load_attr(bogus)


# ------------------------------------------------- live trainers (the fence)

ATTR_KEYS = tuple(f"attr_{c}_ms" for c in stepattr.COMPONENTS) + (
    "attr_device_ms", "attr_comm_ms", "attr_recon_err_ms",
    "data_wait_share")


def _assert_attr_run(path, min_steps):
    recs = read_metrics(path)
    steps = stepattr.step_records(recs)
    assert len(steps) >= min_steps, f"{len(steps)} attr step(s)"
    for r in steps:
        for k in ATTR_KEYS:
            assert k in r, k
    summ = stepattr.summarize(recs)
    assert summ is not None
    # THE acceptance fence: the identity reconciles to <= 0.5% of the
    # p50 step time on a real run
    assert summ["recon_err_pct_p50"] <= 0.5, summ
    # shares are per-component p50s over the step p50 — medians of a
    # skewed run (compile-heavy step 0) don't sum exactly, but must stay
    # in the same ballpark as the closed identity
    assert 75.0 <= sum(summ["shares_pct"].values()) <= 125.0, summ
    # the one-time phases event is booked and anchors a roofline
    ev = stepattr.phase_event(recs)
    assert ev is not None, "trainer must book stepattr_phases once"
    assert stepattr.roofline(summ, ev)["fix_first"]
    assert len([r for r in recs
                if r.get("ft_event") == "stepattr_phases"]) == 1
    return summ


def test_lm_trainer_identity_fence(tmp_path):
    """A real LM fit with step_attr=True stamps the attr_* fields on
    every step and reconciles inside the fence."""
    import jax

    from pytorch_distributed_tpu.models.transformer import TransformerLM
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
    from pytorch_distributed_tpu.train.lm import (
        LMTrainer,
        SyntheticTokenDataset,
    )

    mesh = build_mesh(MeshSpec(("data",), (2,)), jax.devices()[:2])
    model = TransformerLM(vocab_size=32, d_model=32, n_heads=2, n_layers=1)
    ds = SyntheticTokenDataset(64, 16, 32, seed=0)
    path = str(tmp_path / "lm.jsonl")
    hb = str(tmp_path / "hb")
    with mesh:
        t = LMTrainer(model, mesh, ds, batch_size=4, lr=0.05, seed=0,
                      eval_dataset=None, metrics_jsonl=path, hb_dir=hb,
                      hb_interval_s=0.0, step_attr=True)
        t.fit(6, print_freq=3)
    summ = _assert_attr_run(path, 6)
    # on the tiny CPU model, compute dominates — the loader is synthetic
    assert summ["data_wait_share_p50"] < 60.0, summ
    # heartbeats carry the data_wait EMA for the straggler classifier
    from pytorch_distributed_tpu.obs import read_heartbeats

    beats = read_heartbeats(hb)
    assert beats[0].get("data_wait") is not None


@pytest.mark.parametrize("explicit", [False, True],
                         ids=["gspmd", "explicit"])
def test_image_trainer_identity_fence(tmp_path, explicit):
    """The image trainer closes the same identity on both step flavors
    (GSPMD and explicit shard_map collectives)."""
    from pytorch_distributed_tpu.train.config import Config
    from pytorch_distributed_tpu.train.trainer import Trainer

    cfg = Config(arch="resnet18", batch_size=8, epochs=1, lr=0.1,
                 print_freq=2, synthetic=True, synthetic_length=24,
                 image_size=32, num_classes=4, seed=0,
                 checkpoint_dir=str(tmp_path), workers=0,
                 metrics_jsonl=str(tmp_path / "m.jsonl"),
                 step_attr=True)
    Trainer(cfg, explicit_collectives=explicit).fit()
    _assert_attr_run(str(tmp_path / "m.jsonl"), 3)


# ----------------------------------------------------- loader + straggler leg

def test_async_feeder_accounts_waits():
    """AsyncFeeder meters how long the consumer blocked on its queue —
    the data-wait signal when prefetch is on."""
    from pytorch_distributed_tpu.data.loader import AsyncFeeder

    def slow_src():
        for i in range(4):
            time.sleep(0.01)
            yield i

    f = AsyncFeeder(lambda it: it, prefetch=1)
    got = list(f(slow_src()))
    assert got == [0, 1, 2, 3]
    assert f.wait_ms_last >= 0.0
    assert f.wait_ms_ema > 0.0  # the slow source made the consumer wait


def test_find_stragglers_names_input_starved_ranks(tmp_path):
    """A lagging slow rank whose data_wait EMA explains the slowdown is
    named input-starved (loader, not device); an equally slow rank with
    no data wait stays a plain slow rank."""
    from pytorch_distributed_tpu.obs.heartbeat import (
        HeartbeatWriter,
        find_stragglers,
        read_heartbeats,
    )

    d = str(tmp_path)
    now = time.time()
    # three fast front-runners pin the fleet-median EMA low; two ranks
    # lag with a fat EMA — one starved by its loader, one just slow
    fleet = ((0, 20, 0.010, None), (3, 20, 0.010, None),
             (4, 20, 0.010, None), (1, 10, 0.050, 45.0),
             (2, 10, 0.050, 1.0))
    for pid, step, ema, dw in fleet:
        w = HeartbeatWriter(d, process_index=pid, interval_s=0.0,
                            world=5)
        w.beat(step, step_time_ema=ema, data_wait_ms=dw)
    reasons = find_stragglers(read_heartbeats(d), now=now)
    assert 1 in reasons and 2 in reasons and 0 not in reasons
    assert "input-starved" in reasons[1], reasons[1]
    assert "loader, not device" in reasons[1]
    assert "input-starved" not in reasons[2], reasons[2]
    assert "slow rank" in reasons[2]


# ------------------------------------------------------------ CLI + report

def test_obs_roofline_selftest_is_jax_free():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "obs_roofline.py"),
         "--selftest"],
        capture_output=True, text=True, timeout=240)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "obs_roofline selftest: OK" in out.stdout


def test_obs_roofline_fixture_render():
    """The checked-in fixture renders the attribution + roofline report
    and exports the planner profile."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "obs_roofline.py"),
         "--metrics-jsonl",
         os.path.join(REPO, "tests", "data", "stepattr_fixture.jsonl"),
         "--json"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    doc = json.loads(out.stdout)
    assert doc["dominant"] == "compute"
    assert doc["recon_err_pct_p50"] <= 0.5
    labels = {p["phase"]: p["label"] for p in doc["roofline"]["phases"]}
    assert labels["update"] == "hbm-bound"


def _write_attr_jsonl(path, comp, sync, data, other, steps=10):
    """A run whose every step is 100 ms with the given composition."""
    exp = 100.0 - comp - sync - data - other
    with MetricsLogger(path, flush_every=1) as log:
        prof = stepattr.phase_profile({"forward": 1e9}, {"forward": 1e7},
                                      peak_flops=1e12, hbm_bw=1e11)
        log.log_event("stepattr_phases",
                      **stepattr.phase_event_fields(prof))
        for i in range(steps):
            log.log_step(i, step_time=0.100, n_items=8, lr=1e-3,
                         scalars={"loss": 2.0},
                         extra={"attr_compute_ms": comp,
                                "attr_exposed_comm_ms": exp,
                                "attr_host_sync_ms": sync,
                                "attr_data_wait_ms": data,
                                "attr_other_ms": other,
                                "attr_device_ms": comp + exp,
                                "attr_comm_ms": exp,
                                "attr_recon_err_ms": 0.0,
                                "data_wait_share": data})


def test_diff_catches_composition_regressions(tmp_path):
    """Same p50 step time, worse composition: the data_wait_share_p95
    and host_sync_ms_p95 rows must flip the diff to exit 1 — and pass in
    the improvement direction (the fences obs_report --selftest also
    pins, here as the user-facing CLI contract)."""
    base = str(tmp_path / "base.jsonl")
    bad = str(tmp_path / "bad.jsonl")
    _write_attr_jsonl(base, comp=62.0, sync=3.0, data=8.0, other=19.0)
    _write_attr_jsonl(bad, comp=42.0, sync=12.0, data=30.0, other=8.0)
    rep = os.path.join(REPO, "scripts", "obs_report.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    worse = subprocess.run(
        [sys.executable, rep, "--diff", base, bad],
        capture_output=True, text=True, timeout=120, env=env)
    assert worse.returncode == 1, worse.stdout + worse.stderr
    assert "data_wait_share_p95" in worse.stdout
    assert "host_sync_ms_p95" in worse.stdout
    better = subprocess.run(
        [sys.executable, rep, "--diff", bad, base],
        capture_output=True, text=True, timeout=120, env=env)
    assert better.returncode == 0, better.stdout + better.stderr


def test_obs_report_folds_the_attribution_section(tmp_path):
    """The single-run report grows '== attribution ==' with the fence
    numbers, and stays silent without --step-attr records."""
    mpath = str(tmp_path / "m.jsonl")
    _write_attr_jsonl(mpath, comp=62.0, sync=3.0, data=8.0, other=19.0)
    rep = os.path.join(REPO, "scripts", "obs_report.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, rep, "--metrics-jsonl", mpath],
        capture_output=True, text=True, timeout=120, env=env)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "== attribution ==" in out.stdout
    assert "dominant: compute" in out.stdout
    assert "data_wait_share" in out.stdout


# --------------------------------------------------------------- alert rule

def test_data_wait_share_alert_latches_and_clears():
    """The declarative rule: fires past max_pct after warmup, latches,
    clears on recovery — per process."""
    from pytorch_distributed_tpu.obs.alerts import AlertEngine, Rule

    eng = AlertEngine([Rule("data_wait_share", "dw", "warn",
                            {"max_pct": 25.0, "warmup_steps": 2})])
    fired = eng.observe({"step": 1, "process": 0, "step_time": 0.1,
                         "data_wait_share": 90.0})
    assert fired == []  # warmup
    fired = eng.observe({"step": 3, "process": 0, "step_time": 0.1,
                         "data_wait_share": 40.0})
    assert [a.name for a in fired] == ["dw"]
    assert "input-starved" in fired[0].detail
    # latched: no re-fire while still breaching
    assert eng.observe({"step": 4, "process": 0, "step_time": 0.1,
                        "data_wait_share": 41.0}) == []
    assert eng.active()
    # recovery clears
    eng.observe({"step": 5, "process": 0, "step_time": 0.1,
                 "data_wait_share": 5.0})
    assert not eng.active()
