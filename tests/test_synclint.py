"""synclint (ISSUE 18): the cross-rank collective-congruence verifier.

Layers under test — everything except the live-sweep fence is jax-free:

- HLO schedule extraction + replica-group congruence (analysis/hlo.py
  parser extensions + analysis/synclint.py) on checked-in fixtures under
  tests/data/synclint/ — one congruent module and five planted
  incongruences, each of which must fire with the right diagnosis;
- the canonical schedule digest: stable across parses, insensitive to
  instruction renames, pinned via analysis/baseline.json, drift = error;
- the host control-flow desync pass (analysis/astlint.py): rank- and
  data-taint classification, inter-procedural collective propagation,
  '# synclint: agreement' / '# synclint: allow' scoping at statement and
  function scope with asserted line numbers, and the real hot loops
  (synclint.SYNC_SCOPES) currently clean;
- the protocol model check (analysis/syncproto.py): every shipped
  protocol verifies desync-free, every planted local-decision variant
  yields a counterexample naming the divergent collective — statically
  reproducing the PR 13 two-rank hang;
- the live fence: with the recipe sweep warm, annotating every mesh'd
  report with its digest adds ZERO compiles and every digest matches the
  checked-in baseline pin.
"""

import json
import os
import subprocess
import sys

import pytest

from pytorch_distributed_tpu.analysis import astlint, syncproto
from pytorch_distributed_tpu.analysis import hlo as hlo_mod
from pytorch_distributed_tpu.analysis import synclint
from pytorch_distributed_tpu.analysis.report import (
    StepReport,
    baseline_entry,
    diff_against_baseline,
    load_baseline,
)

FIXTURES = os.path.join(os.path.dirname(__file__), "data", "synclint")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fixture(name: str) -> str:
    with open(os.path.join(FIXTURES, name)) as f:
        return f.read()


# ------------------------------------------------------ parser extensions

def test_parse_channel_id():
    line = ("  %ar = f32[64]{0} all-reduce(f32[64]{0} %x), channel_id=7, "
            "replica_groups={{0,1}}, to_apply=%add")
    assert hlo_mod.parse_channel_id(line) == 7
    assert hlo_mod.parse_channel_id("%y = f32[] add(%a, %b)") == -1


def test_parse_replica_group_members_iota():
    line = "... replica_groups=[2,4]<=[8], to_apply=%add"
    assert hlo_mod.parse_replica_group_members(line) == [
        [0, 1, 2, 3], [4, 5, 6, 7]]
    line1 = "... replica_groups=[1,4]<=[4], to_apply=%add"
    assert hlo_mod.parse_replica_group_members(line1) == [[0, 1, 2, 3]]


def test_parse_replica_group_members_explicit_and_pairs():
    assert hlo_mod.parse_replica_group_members(
        "... replica_groups={{0,2},{1,3}}, dims={0}") == [[0, 2], [1, 3]]
    assert hlo_mod.parse_replica_group_members(
        "... replica_groups={}") == [[]]
    assert hlo_mod.parse_replica_group_members(
        "... source_target_pairs={{0,1},{1,0}}") == [[0, 1], [1, 0]]
    assert hlo_mod.parse_replica_group_members(
        "%y = f32[] add(%a, %b)") is None


# ------------------------------------------- schedule + digest (layer 1)

def test_schedule_extraction_order_and_start_folding():
    text = _fixture("good.hlo")
    sched = synclint.extract_schedule(text)
    assert [e.kind for e in sched] == [
        "all-reduce", "reduce-scatter", "collective-permute", "all-gather"]
    assert [e.channel_id for e in sched] == [1, 2, 3, 4]
    assert sched[0].groups == [[0, 1, 2, 3]]          # iota synthesized
    assert sched[1].groups == [[0, 1], [2, 3]]        # explicit braces
    assert sched[2].groups == [[0, 1], [1, 2], [2, 3], [3, 0]]  # pairs


def test_async_pairs_counted_once():
    text = """\
HloModule async
ENTRY %main (p0: f32[64]) -> f32[64] {
  %p0 = f32[64]{0} parameter(0)
  %ar-start = f32[64]{0} all-reduce-start(f32[64]{0} %p0), channel_id=1, replica_groups=[1,4]<=[4], to_apply=%add
  ROOT %ar-done = f32[64]{0} all-reduce-done(f32[64]{0} %ar-start)
}
"""
    sched = synclint.extract_schedule(text)
    assert len(sched) == 1 and sched[0].kind == "all-reduce"


def test_digest_stable_and_rename_insensitive():
    text = _fixture("good.hlo")
    d1 = synclint.schedule_digest(synclint.extract_schedule(text))
    d2 = synclint.schedule_digest(synclint.extract_schedule(text))
    assert d1 == d2 and len(d1) == 64
    # instruction names are compiler-churn, not schedule identity
    renamed = text.replace("%ar ", "%ar.42 ").replace("%ag ", "%ag.7 ")
    d3 = synclint.schedule_digest(synclint.extract_schedule(renamed))
    assert d3 == d1
    # but a changed replica grouping IS schedule identity
    regrouped = text.replace("replica_groups={{0,1},{2,3}}",
                             "replica_groups={{0,2},{1,3}}")
    d4 = synclint.schedule_digest(synclint.extract_schedule(regrouped))
    assert d4 != d1


def test_good_fixture_congruent():
    assert synclint.verify_congruence(
        _fixture("good.hlo"), "good", n_devices=4) == []


@pytest.mark.parametrize("fname,needle", [
    ("bad_dup.hlo", "more than one replica group"),
    ("bad_oob.hlo", "out of range"),
    ("bad_sizes.hlo", "mismatched sizes"),
    ("bad_missing.hlo", "participate in no replica group"),
    ("bad_permute.hlo", "not a permutation"),
])
def test_planted_incongruence_fires(fname, needle):
    findings = synclint.verify_congruence(_fixture(fname), fname,
                                          n_devices=4)
    assert findings, f"{fname} must fire"
    assert all(f.kind == "collective-incongruence" and f.severity == "error"
               for f in findings)
    assert any(needle in f.message for f in findings), findings


def test_unknown_mesh_size_skips_range_and_coverage_checks():
    # without n_devices, out-of-range/coverage can't be judged — but
    # duplicates still can
    assert synclint.verify_congruence(_fixture("bad_oob.hlo"), "x") == []
    assert synclint.verify_congruence(_fixture("bad_dup.hlo"), "x") != []


def test_sync_report_and_digest_diff():
    rep = synclint.sync_report("s", _fixture("good.hlo"), {"data": 4})
    assert rep.sync_digest and not rep.findings
    # unpinned -> warn; matching pin -> clean; drifted pin -> error
    warn = synclint.diff_digest(rep, None)
    assert [f.severity for f in warn] == ["warn"]
    assert synclint.diff_digest(rep, {"sync_digest": rep.sync_digest}) == []
    drift = synclint.diff_digest(rep, {"sync_digest": "f" * 64})
    assert [f.kind for f in drift] == ["sync-digest-drift"]
    assert drift[0].severity == "error"
    assert "audit the reorder" in drift[0].message


def test_digest_rides_baseline_entry_and_full_diff():
    rep = synclint.sync_report("s", _fixture("good.hlo"), {"data": 4})
    entry = baseline_entry(rep)
    assert entry["sync_digest"] == rep.sync_digest
    assert diff_against_baseline(rep, entry) == []
    entry["sync_digest"] = "f" * 64
    drifted = [f for f in diff_against_baseline(rep, entry)
               if f.kind == "sync-digest-drift"]
    assert len(drifted) == 1 and drifted[0].severity == "error"
    # a report without a digest (pre-synclint sweep) never emits the key
    bare = StepReport(name="bare", mesh_shape={"data": 4})
    assert "sync_digest" not in baseline_entry(bare)


# --------------------------------------------- host desync pass (layer 2)

def test_planted_fixture_fires_at_documented_lines():
    findings = astlint.lint_desync_source(
        _fixture("desync_planted.py"), path="p.py", hot_functions=("T.fit",))
    assert sorted(f.where for f in findings) == ["p.py:16", "p.py:19"]
    assert all(f.kind == "collective-desync" and f.severity == "error"
               for f in findings)
    by_line = {f.where: f.message for f in findings}
    assert "rank-dependent branch at p.py:15" in by_line["p.py:16"]
    assert "save_checkpoint()" in by_line["p.py:16"]
    assert "locally-data-dependent branch at p.py:18" in by_line["p.py:19"]
    assert "rollback()" in by_line["p.py:19"]  # inter-procedural via psum


def test_agreement_and_allow_markers_statement_scope():
    assert astlint.lint_desync_source(
        _fixture("agreement_ok.py"), path="a.py",
        hot_functions=("T.fit",)) == []


def test_in_module_planted_fixture():
    findings = synclint.planted_desync_findings()
    assert len(findings) == 2
    msgs = " ".join(f.message for f in findings)
    assert "rank-dependent" in msgs and "locally-data-dependent" in msgs


def test_agreement_marker_on_branch_line():
    src = (
        "def fit(self):\n"
        "    if jax.process_index() == 0:  # synclint: agreement\n"
        "        self.save_checkpoint()\n")
    assert astlint.lint_desync_source(src, "m.py",
                                      hot_functions=("fit",)) == []
    # without the marker the same branch fires
    bare = src.replace("  # synclint: agreement", "")
    fired = astlint.lint_desync_source(bare, "m.py", hot_functions=("fit",))
    assert [f.where for f in fired] == ["m.py:3"]


def test_agreement_marker_as_assignment_taint_sink():
    src = (
        "def fit(self):\n"
        "    flag = self.guard.drain()  # synclint: agreement\n"
        "    if flag:\n"
        "        self.save_checkpoint()\n")
    assert astlint.lint_desync_source(src, "m.py",
                                      hot_functions=("fit",)) == []
    bare = src.replace("  # synclint: agreement", "")
    fired = astlint.lint_desync_source(bare, "m.py", hot_functions=("fit",))
    assert [f.where for f in fired] == ["m.py:4"]
    assert "locally-data-dependent" in fired[0].message


def test_allow_marker_suppresses_single_call():
    src = (
        "def fit(self):\n"
        "    if jax.process_index() == 0:\n"
        "        self.save_checkpoint()  # synclint: allow\n"
        "        self.step_fn()\n")
    fired = astlint.lint_desync_source(src, "m.py", hot_functions=("fit",))
    # only the unsuppressed sibling call fires
    assert [f.where for f in fired] == ["m.py:4"]


def test_function_scope_blessing():
    src = (
        "def fit(self):  # synclint: agreement\n"
        "    if jax.process_index() == 0:\n"
        "        self.save_checkpoint()\n")
    assert astlint.lint_desync_source(src, "m.py",
                                      hot_functions=("fit",)) == []


def test_rank_vs_local_taint_classification():
    src = (
        "def fit(self):\n"
        "    r = jax.process_index()\n"
        "    t = time.monotonic()\n"
        "    if r == 0:\n"
        "        self.step_fn()\n"
        "    if t > 5.0:\n"
        "        self.step_fn()\n")
    fired = astlint.lint_desync_source(src, "m.py", hot_functions=("fit",))
    assert len(fired) == 2
    by_line = {f.where: f.message for f in fired}
    assert "rank-dependent" in by_line["m.py:5"]
    assert "locally-data-dependent" in by_line["m.py:7"]


def test_rank_taint_dominates_local():
    src = (
        "def fit(self):\n"
        "    x = time.monotonic()\n"
        "    x = jax.process_index()\n"
        "    if x:\n"
        "        self.step_fn()\n")
    fired = astlint.lint_desync_source(src, "m.py", hot_functions=("fit",))
    assert len(fired) == 1 and "rank-dependent" in fired[0].message


def test_interprocedural_collective_propagation():
    src = (
        "def helper(state):\n"
        "    return inner(state)\n"
        "def inner(state):\n"
        "    return psum(state, 'data')\n"
        "def fit(self):\n"
        "    if os.getenv('RANK') == '0':\n"
        "        helper(1)\n")
    issuing = astlint.collective_functions(
        __import__("ast").parse(src), astlint.COLLECTIVE_CALLS)
    assert {"helper", "inner"} <= issuing
    fired = astlint.lint_desync_source(src, "m.py", hot_functions=("fit",))
    assert [f.where for f in fired] == ["m.py:7"]
    assert "helper()" in fired[0].message


def test_untainted_branches_are_free():
    src = (
        "def fit(self, steps):\n"
        "    for i in range(steps):\n"
        "        if i % 2 == 0:\n"
        "            self.step_fn()\n")
    assert astlint.lint_desync_source(src, "m.py",
                                      hot_functions=("fit",)) == []


def test_missing_hot_function_raises():
    with pytest.raises(ValueError, match="SYNC_SCOPES"):
        astlint.lint_desync_source("def g():\n    pass\n", "m.py",
                                   hot_functions=("fit",))


def test_real_hot_scopes_currently_clean():
    """The repo's own agreement idioms (preemption agreement, in-step
    all-reduced divergence drain, coordinator-committed membership
    epochs) are anchored; the registered scopes must lint clean."""
    report = synclint.lint_sync_scopes()
    assert report.findings == [], report.findings


def test_sync_scope_registry_names_resolve():
    """Renaming a registered function must fail loudly, not silently
    skip the scope (ValueError carries the registry pointer)."""
    import pytorch_distributed_tpu as pkg

    base = os.path.dirname(os.path.abspath(pkg.__file__))
    for rel, _functions in synclint.SYNC_SCOPES:
        assert os.path.exists(os.path.join(base, rel)), rel


# ------------------------------------------ protocol explorer (layer 3)

def test_shipped_protocols_verify_desync_free():
    findings = syncproto.check_protocols()
    assert len(findings) == len(syncproto.MODELS)
    assert all(f.severity == "info" and f.kind == "protocol-desync"
               for f in findings)
    assert all("verified desync-free" in f.message for f in findings)


def test_planted_variants_all_desync():
    findings = syncproto.planted_counterexamples()
    assert len(findings) == len(syncproto.MODELS)
    assert all(f.severity == "error" for f in findings)
    assert all("local-variant" in f.where for f in findings)


def test_elastic_shrink_counterexample_names_the_collective():
    """The acceptance-criterion story: a locally-decided shrink leaves
    one rank entering the re-mesh gather while its peer has already
    moved on — the explorer must name both sides."""
    cex = syncproto.explore(syncproto.elastic_model(agreed=False))
    assert cex is not None
    msg = str(cex)
    assert "remesh_gather" in msg
    assert "rank0" in msg and "rank1" in msg
    assert cex.blame_var == "shrink"


def test_preempt_counterexample_is_the_pr13_hang():
    cex = syncproto.explore(syncproto.preempt_model(agreed=False))
    assert cex is not None
    # one rank stops (END), the other waits in grad_allreduce forever
    assert "END" in str(cex) and "grad_allreduce" in str(cex)


def test_agreed_models_have_no_counterexample():
    for key, (builder, _desc) in syncproto.MODELS.items():
        assert syncproto.explore(builder(agreed=True)) is None, key


def test_explorer_is_deterministic():
    a = syncproto.explore(syncproto.elastic_model(agreed=False))
    b = syncproto.explore(syncproto.elastic_model(agreed=False))
    assert str(a) == str(b)


# ------------------------------------------------------ CLI + composition

def test_sweep_cached_jax_free(tmp_path):
    """The --hlo-cache path: congruence off persisted artifacts, no jax."""
    cache = tmp_path / "hlo"
    cache.mkdir()
    (cache / "step_a.hlo").write_text(_fixture("good.hlo"))
    (cache / "step_a.json").write_text(json.dumps(
        {"mesh_shape": {"data": 4}, "measured_peak_bytes": 0,
         "arg_classes": {}}))
    (cache / "step_bad.hlo").write_text(_fixture("bad_dup.hlo"))
    (cache / "step_bad.json").write_text(json.dumps(
        {"mesh_shape": {"data": 4}, "measured_peak_bytes": 0,
         "arg_classes": {}}))
    reports = synclint.sweep_cached(str(cache))
    by_name = {r.name: r for r in reports}
    assert set(by_name) == {"step_a", "step_bad"}
    assert by_name["step_a"].findings == []
    assert by_name["step_a"].sync_digest
    assert [f.kind for f in by_name["step_bad"].findings] == [
        "collective-incongruence"]


def test_checked_in_baseline_has_digests_for_all_mesh_steps():
    """Every mesh'd recipe's baseline entry carries a pinned digest (the
    live sweep fence below verifies the values)."""
    baseline = load_baseline(os.path.join(
        REPO, "pytorch_distributed_tpu", "analysis", "baseline.json"))
    missing = [name for name, entry in baseline.items()
               if not entry.get("sync_digest")]
    assert missing == [], f"steps without a pinned digest: {missing}"
    assert len(baseline) >= 18


@pytest.mark.slow
def test_cli_selftest_subprocess():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "synclint.py"),
         "--selftest"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "synclint selftest OK" in r.stdout


def test_annotation_adds_zero_compiles_and_digests_match_baseline(
        get_lowering):
    """The tentpole fence: with the recipe sweep warm, annotating every
    mesh'd report with its collective-schedule digest + congruence
    verdict adds ZERO compiles, every schedule verifies congruent, and
    every digest matches the checked-in pin."""
    from pytorch_distributed_tpu.analysis import core

    for name in core.RECIPES:
        get_lowering(name)
    before = get_lowering.compile_count()

    reports = synclint.sweep()
    assert get_lowering.compile_count() == before, (
        "synclint.sweep must ride the shared lowering cache")
    assert len(reports) >= 18
    baseline = load_baseline(os.path.join(
        REPO, "pytorch_distributed_tpu", "analysis", "baseline.json"))
    for r in reports:
        assert r.findings == [], (r.name, r.findings)
        assert r.sync_digest, r.name
        entry = baseline.get(r.name)
        assert entry is not None, f"{r.name} missing from baseline"
        assert entry.get("sync_digest") == r.sync_digest, (
            f"{r.name}: digest drifted vs baseline — audit the schedule "
            "change, then scripts/synclint.py --update-baseline")

    # the shardlint composition path: annotate in place, still 0 compiles
    sweep_reports = core.analyze_all()
    synclint.annotate_reports(sweep_reports)
    assert get_lowering.compile_count() == before
    annotated = [r for r in sweep_reports
                 if r.name in core.RECIPES and r.mesh_shape]
    assert annotated and all(r.sync_digest for r in annotated)
