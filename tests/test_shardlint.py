"""shardlint (pytorch_distributed_tpu/analysis/): every detector proven
against planted hazards, every fenced-good path proven clean.

Layers under test:
- pure text parsing (analysis/hlo.py) on a hand-written HLO fixture — no
  compilation involved;
- the AST host-sync lint (analysis/astlint.py) on planted sources;
- report/baseline plumbing (analysis/report.py);
- the live analyzer (analysis/core.py) on the 4-way CPU mesh: the
  synthetic bad step must trip all planted hazards, the fused-CE dp/tp
  modes must show zero replicated-[V,D] findings while the replicated
  mode is flagged (the PR-1 regression fence), and the full recipe sweep
  must stay clean against the checked-in collective-budget baseline.
"""

import os
import warnings

import jax
import jax.numpy as jnp
import pytest

from pytorch_distributed_tpu.analysis import (
    Finding,
    StepReport,
    diff_against_baseline,
    load_baseline,
)
from pytorch_distributed_tpu.analysis import astlint, core
from pytorch_distributed_tpu.analysis import hlo as hlo_mod
from pytorch_distributed_tpu.analysis.report import baseline_entry

# A miniature post-optimization module exercising every parsed construct:
# donation aliases, entry layout with tiled-layout annotations, an async
# collective pair, a tuple-typed instruction, and a non-entry computation.
HLO_FIXTURE = """\
HloModule test, input_output_alias={ {0}: (0, {}, MAY_ALIAS), {1}: (2, {}, MUST_ALIAS) }, entry_computation_layout={(f32[64,32]{1,0}, s32[]{:T(256)}, f32[64,32]{1,0})->(f32[64,32]{1,0}, f32[]{:T(256)})}

%add_comp (x: f32[], y: f32[]) -> f32[] {
  %x = f32[] parameter(0)
  %y = f32[] parameter(1)
  ROOT %add.1 = f32[] add(f32[] %x, f32[] %y)
}

ENTRY %main (p0: f32[64,32], p1: s32[], p2: f32[64,32]) -> (f32[64,32], f32[]) {
  %p0 = f32[64,32]{1,0} parameter(0)
  %p2 = f32[64,32]{1,0} parameter(2)
  %mul = f32[64,32]{1,0} multiply(f32[64,32]{1,0} %p0, f32[64,32]{1,0} %p2)
  %ar-start = f32[64,32]{1,0} all-reduce-start(f32[64,32]{1,0} %mul), replica_groups=[1,4]<=[4], to_apply=%add_comp
  %ar-done = f32[64,32]{1,0} all-reduce-done(f32[64,32]{1,0} %ar-start)
  %fus = f32[64,32]{1,0} fusion(f32[64,32]{1,0} %ar-done), kind=kLoop, calls=%add_comp
  %ag = f32[256,32]{1,0} all-gather(f32[64,32]{1,0} %fus), dimensions={0}
  %c = f32[] constant(0)
  ROOT %tup = (f32[64,32]{1,0}, f32[]) tuple(f32[64,32]{1,0} %fus, f32[] %c)
}
"""


# ------------------------------------------------------------- hlo parsing

def test_parse_instructions_opcodes_and_computations():
    instrs = hlo_mod.parse_instructions(HLO_FIXTURE)
    by_name = {i.name: i for i in instrs}
    assert by_name["mul"].opcode == "multiply"
    assert by_name["mul"].computation == "main"
    assert by_name["add.1"].computation == "add_comp"
    assert by_name["add.1"].is_root
    assert by_name["mul"].shapes == [("f32", (64, 32))]
    # tuple result type contributes every member shape
    assert by_name["tup"].shapes == [("f32", (64, 32)), ("f32", ())]
    assert by_name["tup"].result_bytes() == 64 * 32 * 4 + 4


def test_collectives_async_pair_counted_once():
    coll = hlo_mod.collect_collectives(hlo_mod.parse_instructions(HLO_FIXTURE))
    # -start carries the payload, -done is bookkeeping
    assert coll["all-reduce"] == {"count": 1, "bytes": 64 * 32 * 4}
    assert coll["all-gather"] == {"count": 1, "bytes": 256 * 32 * 4}


def test_alias_map_and_entry_layout():
    assert hlo_mod.parse_input_output_alias(HLO_FIXTURE) == [
        ((0,), 0, ()), ((1,), 2, ())]
    assert hlo_mod.aliased_param_numbers(HLO_FIXTURE) == [0, 2]
    assert hlo_mod.entry_parameter_shapes(HLO_FIXTURE) == [
        ("f32", (64, 32)), ("s32", ()), ("f32", (64, 32))]
    assert hlo_mod.entry_output_shapes(HLO_FIXTURE) == [
        ("f32", (64, 32)), ("f32", ())]


def test_find_materializations_excludes_root_and_filters_opcode():
    hits = hlo_mod.find_materializations(HLO_FIXTURE, "f32", (64, 32))
    assert [i.name for i in hits] == ["fus"]  # only the fusion producer
    any_op = hlo_mod.find_materializations(
        HLO_FIXTURE, "f32", (64, 32), opcodes=None)
    assert "mul" in [i.name for i in any_op]
    assert "p0" not in [i.name for i in any_op]  # parameters excluded


# ---------------------------------------------------------------- astlint

PLANTED = """\
import numpy as np


class T:
    def fit(self, steps):
        for i in range(steps):
            state, metrics = self.step(state)
            x = float(metrics["loss"])
            y = np.asarray(metrics["acc"])
            metrics["loss"].block_until_ready()
            ok = float(metrics["t"])  # shardlint: allow-sync
            f = lambda v: float(v)
        done = float(metrics["loss"])
        return done

    def cold(self, rows):
        for r in rows:
            out = float(r)
        return out
"""


def test_planted_syncs_detected_with_lines():
    findings = astlint.lint_source(PLANTED, "planted.py",
                                   hot_functions=("T.fit",))
    assert len(findings) == 3
    assert all(f.kind == "host-sync" and f.severity == "error"
               for f in findings)
    lines = sorted(int(f.where.rsplit(":", 1)[1]) for f in findings)
    assert lines == [8, 9, 10]


def test_sync_outside_loop_and_non_hot_function_ignored():
    # the float() after the loop (line 13) and everything in cold() is
    # out of scope; the lambda body inside the loop is a definition
    findings = astlint.lint_source(PLANTED, "planted.py",
                                   hot_functions=("T.fit",))
    assert all(int(f.where.rsplit(":", 1)[1]) <= 10 for f in findings)


def test_all_functions_hot_when_unspecified():
    findings = astlint.lint_source(PLANTED, "planted.py")
    assert len(findings) == 4  # + the one in cold()


def test_missing_hot_function_raises():
    with pytest.raises(ValueError, match="not found"):
        astlint.lint_source(PLANTED, "planted.py",
                            hot_functions=("T.gone",))


def test_registered_hot_loops_are_clean():
    report = core.lint_hot_loops()
    assert report.findings == []


# ------------------------------------------------------- report / baseline

def test_finding_vocabulary_enforced():
    with pytest.raises(ValueError):
        Finding(kind="bogus", severity="error", where="x", message="m")
    with pytest.raises(ValueError):
        Finding(kind="host-sync", severity="fatal", where="x", message="m")


def test_baseline_diff_regression_improvement_and_missing_entry():
    rep = StepReport(name="s", mesh_shape={"data": 4},
                     collectives={"all-reduce": {"count": 3, "bytes": 300}})
    base = baseline_entry(rep)
    assert diff_against_baseline(rep, base) == []
    worse = StepReport(name="s", mesh_shape={"data": 4}, collectives={
        "all-reduce": {"count": 3, "bytes": 300},
        "all-gather": {"count": 1, "bytes": 64}})
    regress = diff_against_baseline(worse, base)
    # the new kind trips the per-kind budget AND the per-step total budget
    assert [f.severity for f in regress] == ["error", "error"]
    assert {f.kind for f in regress} == {"collective-regression"}
    assert {f.where for f in regress} == {"s:all-gather", "s:total"}
    better = StepReport(name="s", mesh_shape={"data": 4},
                        collectives={"all-reduce": {"count": 2, "bytes": 200}})
    assert [f.severity for f in diff_against_baseline(better, base)] == [
        "info"]
    missing = diff_against_baseline(rep, None)
    assert [f.severity for f in missing] == ["warn"]


# --------------------------------------------------------- live analyzer

def test_synthetic_bad_step_trips_every_planted_hazard():
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # XLA's unusable-donation warning
        # memoized: shares the one synthetic-bad compile with
        # core.selftest (the compile-budget assert counts on it)
        rep = core.analyze_lowering(core.get_synthetic_bad_lowering())
    kinds = {f.kind for f in rep.findings}
    assert kinds == {"replicated-large-tensor", "dtype-promotion",
                     "lost-donation"}
    repl = rep.by_kind("replicated-large-tensor")
    assert [f.shape for f in repl] == [(2048, 128)]
    assert repl[0].severity == "error"
    assert "loop-carried" in repl[0].message
    prom = rep.by_kind("dtype-promotion")
    assert prom[0].dtype == "f32" and prom[0].bytes == 8 * 65536 * 4
    lost = rep.by_kind("lost-donation")
    assert lost[0].severity == "error"
    assert rep.donation["missing"] == [0]


def test_fused_ce_fence_replicated_flagged_dp_tp_clean(get_lowering):
    """The PR-1 regression fence: the replicated fused-CE mode carries the
    full [V, D] dE accumulator on every device of the data mesh; the dp
    and tp shardings must eliminate it entirely."""
    V, D = core._LM["vocab"], core._LM["d_model"]
    bad = core.analyze_lowering(get_lowering("lm_fused_ce_replicated"),
                                min_replicated_bytes=4096)
    flagged = bad.by_kind("replicated-large-tensor")
    assert any(f.shape == (V, D) for f in flagged), bad.findings
    for mode in ("lm_fused_ce_dp", "lm_fused_ce_tp"):
        good = core.analyze_lowering(get_lowering(mode),
                                     min_replicated_bytes=4096)
        assert good.by_kind("replicated-large-tensor") == [], (
            mode, good.findings)


def test_train_step_donations_fully_aliased(get_lowering):
    for name in ("lm_train_dp", "lm_pp_1f1b"):
        rep = core.analyze_lowering(get_lowering(name))
        assert rep.donation["missing"] == [], (name, rep.donation)
        assert rep.by_kind("lost-donation") == []
        assert rep.donation["aliased"] == rep.donation["expected"]


def test_no_donation_opportunity_warns():
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = core._mesh(("data",), (4,))
    rep_sh = NamedSharding(mesh, P())
    f = jax.jit(lambda s: (s * 0.9, jnp.sum(s)),
                in_shardings=(rep_sh,), out_shardings=(rep_sh, rep_sh))
    s = jnp.ones((512, 512), jnp.float32)  # 1 MiB, shape-matches output 0
    rep = core.analyze_jitted(f, (s,), name="undonated", mesh=mesh,
                              donate=())
    warns = rep.by_kind("no-donation")
    assert len(warns) == 1 and warns[0].severity == "warn"
    assert rep.donation["opportunity_bytes"] == 512 * 512 * 4


def test_selftest_passes():
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        summary = core.selftest()
    assert summary["ok"]


def test_recipe_sweep_clean_against_checked_in_baseline():
    """The tier-1 fence: every recipe's step analyzed on the 4-way CPU
    mesh — zero error findings, and per-step collective budgets exactly
    match analysis/baseline.json (regenerate deliberately with
    ``scripts/shardlint.py --update-baseline``)."""
    reports = core.analyze_all()
    assert {r.name for r in reports} == set(core.RECIPES) | {"hot-loops"}
    baseline = load_baseline(core.baseline_path())
    for r in reports:
        if r.mesh_shape:
            for f in diff_against_baseline(r, baseline.get(r.name)):
                r.add(f)
        assert r.errors() == [], (r.name, r.findings)
    # the donation audit holds across every train step builder
    for r in reports:
        if r.donation.get("expected"):
            assert r.donation["missing"] == [], (r.name, r.donation)


@pytest.mark.slow
def test_cli_selftest_subprocess():
    """The CLI entry point end to end (separate process, own XLA_FLAGS)."""
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    out = subprocess.run(
        [sys.executable, os.path.join(root, "scripts", "shardlint.py"),
         "--selftest"],
        capture_output=True, text=True, timeout=600, env=env, cwd=root)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "selftest OK" in out.stdout
