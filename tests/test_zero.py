"""ZeRO-1 weight-update sharding (parallel/zero.py, ``--zero wus``) fences.

Covers the ISSUE-9 contracts on the simulated CPU mesh:

- step parity: 3 explicit-collective steps under wus track the replicated
  DP step bit-tight in f32 and loosely composed with int8 grad compression;
- GSPMD composition: LM training with ``zero_momentum_specs`` matches the
  replicated run and actually holds 1/N momentum shards;
- gather/shard round-trip: the stacked-chunk momentum layout flattens to
  the param-shaped tree and re-chunks exactly;
- checkpoints: sharded momentum round-trips through the param-shaped disk
  layout, and mode-switch restores work in BOTH directions
  (legacy-replicated -> wus, wus -> replicated);
- kill-and-resume parity under ``--zero wus`` (the ISSUE-9 acceptance
  criterion), riding the test_ft preemption drill;
- shardlint: ``declared_zero`` promotes the replicated-state info finding
  to a hard error, while the real zero recipes stay green;
- analytic wire parity: RS+AG wire bytes equal the ring all-reduce's
  (obs/flops.py zero_wire_parity), and the analytic model lands within
  the ±15% residual window of the compiled train_image_zero ledger.
"""

import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.ft import ChaosSchedule, SignalAt
from pytorch_distributed_tpu.ops import qcomm
from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
from pytorch_distributed_tpu.parallel import zero as zero_lib
from pytorch_distributed_tpu.train.checkpoint import (
    CHECKPOINT_NAME,
    load_checkpoint,
    save_checkpoint,
)
from pytorch_distributed_tpu.train.lm import LMTrainer
from pytorch_distributed_tpu.train.optim import sgd_init
from pytorch_distributed_tpu.train.state import TrainState
from pytorch_distributed_tpu.train.steps import make_train_step

from tests.test_steps import _MLP, _leaves_allclose

N = 4


def _mesh4():
    return build_mesh(MeshSpec(("data",), (N,)), jax.devices()[:N])


@pytest.fixture(scope="module")
def mlp_variables():
    """One init trace for every momentum-layout test in the module (the
    compile-budget discipline: tests/conftest.py ``lm_world32``)."""
    model = _MLP(classes=10)
    return model, model.init(jax.random.PRNGKey(0),
                             jnp.zeros((1, 8, 8, 3)))


def _batches(k=3, seed=4):
    rng = np.random.default_rng(seed)
    return [{
        "images": rng.normal(size=(16, 8, 8, 3)).astype(np.float32),
        "labels": rng.integers(0, 10, size=16).astype(np.int32),
        "weights": np.ones(16, np.float32),
    } for _ in range(k)]


def _run_explicit(model, variables, mesh, zero, grad_compress="none"):
    v = jax.tree_util.tree_map(jnp.array, variables)
    if zero == "wus":
        momentum = zero_lib.init_wus_momentum(
            v["params"], N, quantized=grad_compress in qcomm.QUANTIZED_MODES)
    else:
        momentum = sgd_init(v["params"])
    residual = qcomm.init_residual(v["params"], grad_compress,
                                   explicit=True, n_data=N)
    state = TrainState.create(v, momentum, residual=residual)
    step = make_train_step(model, mesh, explicit_collectives=True,
                           grad_compress=grad_compress, zero=zero)
    for b in _batches():
        state, metrics = step(state, b, jnp.float32(0.1))
    return state, float(metrics["loss"])


# ------------------------------------------------------------- step parity

def test_wus_step_parity_vs_replicated(mlp_variables):
    """The ISSUE-9 numerics fence: 3 explicit steps on the 4-way mesh.
    f32 wus IS the replicated update (reduce-scatter + chunked SGD +
    delta all-gather reassociates the same math) — tight tolerance;
    int8 wus composes with error feedback — loose tolerance."""
    mesh = _mesh4()
    model, variables = mlp_variables
    s_repl, loss_repl = _run_explicit(model, variables, mesh, "none")
    s_wus, loss_wus = _run_explicit(model, variables, mesh, "wus")
    np.testing.assert_allclose(loss_wus, loss_repl, rtol=2e-5)
    _leaves_allclose(s_repl.params, s_wus.params, rtol=2e-5)
    # momentum actually lives 1/N-sharded
    for leaf in jax.tree_util.tree_leaves(s_wus.momentum):
        assert leaf.addressable_shards[0].data.size * N == leaf.size

    s_q, loss_q = _run_explicit(model, variables, mesh, "wus", "int8")
    np.testing.assert_allclose(loss_q, loss_repl, rtol=5e-3)
    _leaves_allclose(s_repl.params, s_q.params, rtol=0.05, atol=5e-3)
    # both quantized hops carry live error feedback
    assert sum(float(jnp.sum(jnp.abs(l)))
               for l in jax.tree_util.tree_leaves(s_q.residual)) > 0.0
    assert sum(float(jnp.sum(jnp.abs(l)))
               for l in jax.tree_util.tree_leaves(s_q.momentum["agerr"])) > 0.0


def test_gspmd_lm_zero_parity_and_sharding(lm_world32, lm_wus_ref_fit):
    """GSPMD composition: LMTrainer with zero='wus' (momentum resharded by
    zero_momentum_specs, from the session-shared reference fit) matches
    the replicated run on identical synthetic batches, and its biggest
    momentum shard is 1/N of the replicated one."""
    mesh, model, ds = lm_world32
    t_wus, loss_wus = lm_wus_ref_fit
    with mesh:
        t_repl = LMTrainer(model, mesh, ds, batch_size=8, lr=0.05,
                           eval_dataset=None, zero=None)
        loss_repl = t_repl.fit(8, print_freq=4)
    np.testing.assert_allclose(loss_wus, loss_repl, rtol=2e-5)
    _leaves_allclose(t_repl.state.params, t_wus.state.params, rtol=2e-5)

    def max_shard(state):
        return max(l.addressable_shards[0].data.size
                   for l in jax.tree_util.tree_leaves(state.momentum))

    assert max_shard(t_wus.state) * jax.device_count() \
        <= max_shard(t_repl.state)


# --------------------------------------------------- momentum layout + disk

def _nonzero_wus(params, quantized=False):
    mom = zero_lib.init_wus_momentum(params, N, quantized=quantized)
    rng = np.random.default_rng(7)
    mom["buf"] = jax.tree_util.tree_map(
        lambda b: jnp.asarray(rng.normal(size=b.shape).astype(np.float32)),
        mom["buf"])
    # Zero the dead padding tail of the last chunk (gather drops it, so a
    # round-trip comparison must not depend on it).
    mom["buf"] = zero_lib.shard_momentum(
        zero_lib.gather_momentum(mom, params), mom["buf"])
    return mom


def test_gather_shard_momentum_roundtrip(mlp_variables):
    """gather(...) flattens the stacked chunks to the exact param-shaped
    tree; shard(...) re-chunks it back bit-exactly (padding dropped)."""
    _, variables = mlp_variables
    params = variables["params"]
    mom = _nonzero_wus(params)
    gathered = zero_lib.gather_momentum(mom, params)
    for g, p in zip(jax.tree_util.tree_leaves(gathered),
                    jax.tree_util.tree_leaves(params)):
        assert np.shape(g) == np.shape(p)
    rechunked = zero_lib.shard_momentum(gathered, mom["buf"])
    _leaves_allclose(rechunked, mom["buf"], rtol=0, atol=0)


def test_checkpoint_sharded_momentum_roundtrip(tmp_path, mlp_variables):
    """Disk always stores the param-shaped momentum (gather-on-save); a
    wus template re-chunks it on restore with agerr reset to zeros."""
    _, variables = mlp_variables
    state = TrainState.create(
        variables, _nonzero_wus(variables["params"], quantized=True))
    path = save_checkpoint(str(tmp_path), state, 0, "mlp", 0.0, False)

    template = TrainState.create(
        jax.tree_util.tree_map(jnp.zeros_like, variables),
        zero_lib.init_wus_momentum(variables["params"], N, quantized=True))
    loaded, _ = load_checkpoint(path, template)
    _leaves_allclose(loaded.momentum["buf"], state.momentum["buf"],
                     rtol=0, atol=0)
    for leaf in jax.tree_util.tree_leaves(loaded.momentum["agerr"]):
        np.testing.assert_array_equal(np.asarray(leaf), 0.0)


def test_checkpoint_mode_switch_both_directions(tmp_path, mlp_variables):
    """legacy-replicated -> wus and wus -> replicated both restore: the
    param-shaped disk layout is the lingua franca."""
    _, variables = mlp_variables
    rng = np.random.default_rng(9)
    repl_mom = jax.tree_util.tree_map(
        lambda p: jnp.asarray(rng.normal(size=np.shape(p))
                              .astype(np.float32)),
        variables["params"])

    # replicated save -> wus restore
    repl_state = TrainState.create(variables, repl_mom)
    p1 = save_checkpoint(str(tmp_path / "a"), repl_state, 0, "mlp",
                         0.0, False)
    wus_template = TrainState.create(
        jax.tree_util.tree_map(jnp.zeros_like, variables),
        zero_lib.init_wus_momentum(variables["params"], N))
    as_wus, _ = load_checkpoint(p1, wus_template)
    _leaves_allclose(
        zero_lib.gather_momentum(as_wus.momentum, as_wus.params),
        repl_mom, rtol=0, atol=0)

    # wus save -> replicated restore
    wus_state = TrainState.create(variables,
                                  _nonzero_wus(variables["params"]))
    p2 = save_checkpoint(str(tmp_path / "b"), wus_state, 0, "mlp",
                         0.0, False)
    repl_template = TrainState.create(
        jax.tree_util.tree_map(jnp.zeros_like, variables),
        sgd_init(variables["params"]))
    as_repl, _ = load_checkpoint(p2, repl_template)
    _leaves_allclose(
        as_repl.momentum,
        zero_lib.gather_momentum(wus_state.momentum, wus_state.params),
        rtol=0, atol=0)


def test_wus_kill_and_resume_parity(tmp_path, lm_world32, lm_wus_ref_fit):
    """ISSUE-9 acceptance: a --zero wus run preempted mid-stream resumes
    through the gather-on-save/shard-on-restore layout and finishes with
    the SAME final parameters and loss as the uninterrupted wus run
    (the session-shared reference fit)."""
    from pytorch_distributed_tpu.utils.preempt import PreemptionGuard

    mesh, model, ds = lm_world32
    ref, loss_ref = lm_wus_ref_fit
    d = str(tmp_path / "ckpt")

    def trainer(**kw):
        return LMTrainer(model, mesh, ds, batch_size=8, lr=0.05,
                         eval_dataset=None, zero="wus", **kw)

    with mesh:
        guard = PreemptionGuard(signals=(signal.SIGUSR1,)).install()
        try:
            t1 = trainer(checkpoint_dir=d, save_steps=2, preempt=guard,
                         chaos=ChaosSchedule(SignalAt(4, signal.SIGUSR1)))
            t1.fit(8, print_freq=1)
        finally:
            guard.uninstall()
        stop = int(t1.state.step)
        assert 0 < stop < 8

        t2 = trainer(checkpoint_dir=d,
                     resume=os.path.join(d, CHECKPOINT_NAME))
        assert t2._start_step == stop
        loss2 = t2.fit(8, print_freq=4)
    assert loss2 == pytest.approx(loss_ref, rel=1e-6)
    _leaves_allclose(jax.device_get(ref.state.params),
                     jax.device_get(t2.state.params), rtol=1e-6, atol=1e-7)


# ------------------------------------------------------- shardlint + wires

def test_shardlint_declared_zero_promotes_to_error(get_lowering):
    """A GSPMD step that keeps replicated param-shaped momentum is an info
    note under plain DP but a hard error once the step declares --zero wus
    (the sharding silently fell back)."""
    from pytorch_distributed_tpu.analysis import core

    low = get_lowering("train_image_gspmd")
    plain = core.analyze_lowering(low, min_replicated_bytes=1)
    infos = [f for f in plain.findings if f.kind == "replicated-state"]
    assert infos and all(f.severity == "info" for f in infos)

    declared = core.analyze_lowering(low, min_replicated_bytes=1,
                                     declared_zero=True)
    errors = [f for f in declared.findings if f.kind == "replicated-state"]
    assert errors and all(f.severity == "error" for f in errors)


def test_shardlint_zero_recipes_green(get_lowering):
    """The real zero recipes carry no replicated optimizer state and no
    error findings — at the declared_zero severity analyze_recipe applies
    to them (analysis.core.ZERO_RECIPES)."""
    from pytorch_distributed_tpu.analysis import core

    for name in sorted(core.ZERO_RECIPES):
        rep = core.analyze_lowering(get_lowering(name), declared_zero=True)
        assert not [f for f in rep.findings
                    if f.kind == "replicated-state"], (name, rep.findings)
        assert not [f for f in rep.findings
                    if f.severity == "error"], (name, rep.findings)
    kinds = {
        e.kind for e in __import__(
            "pytorch_distributed_tpu.obs.comms",
            fromlist=["comms"]).ledger_from_hlo_text(
            get_lowering("train_image_zero").text).entries}
    assert {"reduce-scatter", "all-gather"} <= kinds


def test_zero_wire_parity_and_analytic_fence(get_lowering):
    """RS+AG wire bytes == the ring all-reduce's (ratio ~1, padding
    aside), for every compression mode; and the analytic model lands
    within ±15% of the compiled train_image_zero ledger."""
    from pytorch_distributed_tpu.obs import comms
    from pytorch_distributed_tpu.obs.flops import (
        comm_residual_pct,
        image_comm_bytes_zero,
        zero_wire_parity,
    )

    low = get_lowering("train_image_zero")
    leaf_sizes = [l.size for l in
                  jax.tree_util.tree_leaves(low.args[0].params)]
    for mode in ("none", "bf16", "int8"):
        parity = zero_wire_parity(leaf_sizes, dp=N, mode=mode)
        assert 0.98 <= parity["ratio"] <= 1.02, (mode, parity)

    lg = comms.ledger_from_hlo_text(low.text, step="train_image_zero",
                                    mesh_shape=low.mesh_shape)
    pred = image_comm_bytes_zero(leaf_sizes, dp=N)
    assert comm_residual_pct(pred.total_bytes, lg.total_bytes) <= 15.0, (
        pred.total_bytes, lg.total_bytes)
