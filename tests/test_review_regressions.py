"""Regressions for code-review findings: num_classes inference order,
explicit-precision precedence, evaluate return value, --pretrained wiring."""

import os

import numpy as np
import pytest

from pytorch_distributed_tpu.train.config import Config
from pytorch_distributed_tpu.train.trainer import Trainer


def _imagefolder(tmp_path, classes=3, per_class=4, size=32):
    from PIL import Image

    rng = np.random.default_rng(0)
    for split in ("train", "val"):
        for c in range(classes):
            d = tmp_path / split / f"cls{c}"
            d.mkdir(parents=True)
            for i in range(per_class):
                arr = rng.integers(0, 256, size=(size, size, 3)).astype(np.uint8)
                Image.fromarray(arr).save(d / f"{i}.png")
    return str(tmp_path)


def _cfg(tmp_path, **kw):
    base = dict(
        arch="resnet18", batch_size=8, epochs=1, print_freq=1, seed=0,
        synthetic=True, synthetic_length=16, image_size=32, num_classes=4,
        checkpoint_dir=str(tmp_path), workers=2,
    )
    base.update(kw)
    return Config(**base)


def test_imagefolder_num_classes_sizes_the_head(tmp_path):
    root = _imagefolder(tmp_path / "data", classes=3)
    cfg = _cfg(tmp_path, synthetic=False, data=root, num_classes=1000)
    t = Trainer(cfg)
    assert cfg.num_classes == 3
    fc_kernel = t.state.params["fc"]["kernel"]
    assert fc_kernel.shape[-1] == 3  # head sized by inferred classes


def test_explicit_precision_fp32_wins_over_recipe_default(tmp_path, monkeypatch):
    from pytorch_distributed_tpu.recipes import apex_distributed

    monkeypatch.chdir(tmp_path)
    captured = {}
    import pytorch_distributed_tpu.recipes._common as common

    orig = common.Trainer

    class SpyTrainer(orig):
        def __init__(self, cfg, **kw):
            captured["precision"] = cfg.precision
            super().__init__(cfg, **kw)

    monkeypatch.setattr(common, "Trainer", SpyTrainer)
    args = ["--synthetic", "--synthetic-length", "16", "-a", "resnet18",
            "--image-size", "32", "--num-classes", "2", "-b", "8",
            "--epochs", "1", "--checkpoint-dir", str(tmp_path)]
    apex_distributed.main(args + ["--precision", "fp32"])
    assert captured["precision"] == "fp32"
    apex_distributed.main(args)
    assert captured["precision"] == "bf16"  # recipe default when unset


def test_evaluate_returns_measured_accuracy(tmp_path):
    t = Trainer(_cfg(tmp_path, num_classes=2, evaluate=True))
    acc = t.fit()
    # Must be the measured value, not the stale best_acc1=0 (a 2-class random
    # head is essentially never exactly 0% on 16 samples... but accept 0<=.
    assert acc == pytest.approx(t.validate(), abs=1e-6)


def test_pretrained_missing_weights_fails_loudly(tmp_path, monkeypatch):
    monkeypatch.setenv("PTD_TPU_PRETRAINED_DIR", str(tmp_path / "nowhere"))
    with pytest.raises(FileNotFoundError, match="--pretrained"):
        Trainer(_cfg(tmp_path, pretrained=True))


@pytest.mark.parametrize("nodelist,expected", [
    ("tpu-host[01-04]", "tpu-host01"),        # dashed prefix + bracket range
    ("gpu-node-01", "gpu-node-01"),           # plain dashed hostname intact
    ("n[001,005-008],n[100]", "n001"),        # comma inside brackets
    ("hosta,hostb", "hosta"),
    ("", "127.0.0.1"),
])
def test_slurm_first_host_handles_dashed_names(nodelist, expected):
    """Advisor round-1 finding: 'gpu-node-01' must not resolve to 'gpu'."""
    from pytorch_distributed_tpu.parallel.dist import _first_slurm_host

    assert _first_slurm_host(nodelist) == expected


def test_wire_dtype_gspmd_warns_numerics_only():
    """Advisor round-1 finding: GSPMD-mode wire_dtype does not compress the
    collective wire; the API must say so."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
    from pytorch_distributed_tpu.train.steps import make_train_step
    from tests.test_steps import _MLP

    mesh = build_mesh(MeshSpec(("data",), (8,)), jax.devices()[:8])
    with pytest.warns(UserWarning, match="NUMERICS emulation"):
        make_train_step(_MLP(classes=2), mesh, wire_dtype=jnp.bfloat16)


def test_accum_zero_rejected_before_step_build(tmp_path):
    """Advisor round-1 finding: validation must precede make_train_step so
    accum_steps=0 raises the clear ValueError, not a trace-time reshape."""
    with pytest.raises(ValueError, match="--accum-steps"):
        Trainer(_cfg(tmp_path, accum_steps=0))


def test_pretrained_loads_saved_checkpoint(tmp_path, monkeypatch, capsys):
    t = Trainer(_cfg(tmp_path, num_classes=4))
    from pytorch_distributed_tpu.train.checkpoint import save_checkpoint

    pdir = tmp_path / "zoo"
    save_checkpoint(str(pdir), t.state, 0, "resnet18", 50.0, is_best=False)
    os.rename(pdir / "checkpoint.msgpack", pdir / "resnet18.msgpack")
    monkeypatch.setenv("PTD_TPU_PRETRAINED_DIR", str(pdir))

    t2 = Trainer(_cfg(tmp_path, num_classes=4, pretrained=True))
    assert "using pre-trained model" in capsys.readouterr().out
    import jax

    for a, b in zip(jax.tree_util.tree_leaves(t.state.params),
                    jax.tree_util.tree_leaves(t2.state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fused_ce_bwd_weights_cotangent_finite_difference():
    """ADVICE r5 (fused_ce.py): _bwd must return a real ``weights``
    cotangent, not None — a future differentiable per-token loss mask
    would otherwise silently train on zero gradient.  Pinned against a
    central finite difference so the fix can't regress to a zero/None
    cotangent that merely matches another analytic path's bug."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.ops.fused_ce import fused_ce_sums

    rng = np.random.default_rng(21)
    h = jnp.asarray(rng.normal(0, 1, size=(8, 6)), jnp.float32)
    e = jnp.asarray(rng.normal(0, 1, size=(10, 6)), jnp.float32)
    t = jnp.asarray(rng.integers(0, 10, size=(8,)), jnp.int32)
    w = jnp.asarray(rng.uniform(0.5, 1.5, size=(8,)), jnp.float32)

    loss = lambda w: fused_ce_sums(h, e, t, w, 2)[0]  # noqa: E731
    gw = jax.grad(loss)(w)
    assert gw is not None and float(jnp.max(jnp.abs(gw))) > 0.0
    eps = 1e-3
    for i in (0, 3, 7):
        basis = jnp.zeros_like(w).at[i].set(eps)
        fd = (float(loss(w + basis)) - float(loss(w - basis))) / (2 * eps)
        np.testing.assert_allclose(float(gw[i]), fd, rtol=5e-3, atol=1e-4)
