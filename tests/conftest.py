"""Test configuration: simulate an 8-device TPU mesh on CPU.

Multi-device DP semantics (gradient psum, sharded batches, set_epoch
reshuffle) are testable with no TPU and no cluster via XLA's host-platform
device-count override — the test strategy SURVEY.md §4 prescribes for the
framework (the reference itself has no tests).

This container pre-imports jax in every process (a sitecustomize on
PYTHONPATH registers the tunneled-TPU "axon" PJRT plugin and sets
JAX_PLATFORMS=axon), so plain env-before-import doesn't work here.  Backends
initialize lazily, though, so overriding the config *after* import but before
first device use reliably lands the tests on the simulated CPU mesh.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402  (may already be imported by sitecustomize)

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

# NOTE on suite runtime: the suite is compile-dominated and serialized on
# the 1-core CI host.  jax's persistent compilation cache
# (jax_compilation_cache_dir) was tried here and REVERTED: on this
# jax/jaxlib (0.4.37, CPU backend) re-executing a deserialized cached
# executable aborts the process ("Fatal Python error: Aborted" in
# test_trainer's train step).  Don't re-enable without upgrading jaxlib
# and re-running the full suite twice (populate + warm) to completion.

# Installs the jax API compat shims (jax.shard_map / lax.axis_size on
# 0.4.x) before any test module does ``from jax import shard_map``.
import pytorch_distributed_tpu  # noqa: E402,F401

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def lm_world32():
    """Session-shared tiny-LM training world: the full-device data mesh,
    the vocab-32 1-layer TransformerLM, and its synthetic dataset.

    Several suites fit this identical configuration (test_zero's parity
    and kill-resume drills, and anything else on the vocab-32 smoke
    model); sharing the objects keeps model.init traced once and — more
    importantly — lets fitted-trainer fixtures below amortize whole
    train-step compiles across tests on the 1-core CI host."""
    from pytorch_distributed_tpu.models.transformer import TransformerLM
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
    from pytorch_distributed_tpu.train.lm import SyntheticTokenDataset

    mesh = build_mesh(MeshSpec(("data",), (jax.device_count(),)))
    model = TransformerLM(vocab_size=32, d_model=32, n_heads=2, n_layers=1)
    ds = SyntheticTokenDataset(64, 16, 32)
    return mesh, model, ds


@pytest.fixture(scope="session")
def lm_wus_ref_fit(lm_world32):
    """The uninterrupted ``--zero wus`` reference run (8 steps, lr 0.05,
    batch 8): one compile + one fit for every test that needs the wus
    baseline (replicated-parity fences, kill-and-resume parity).  Tests
    must treat the returned trainer as read-only."""
    from pytorch_distributed_tpu.train.lm import LMTrainer

    mesh, model, ds = lm_world32
    with mesh:
        t = LMTrainer(model, mesh, ds, batch_size=8, lr=0.05,
                      eval_dataset=None, zero="wus")
        loss = t.fit(8, print_freq=4)
    return t, loss


@pytest.fixture(scope="session")
def get_lowering(tmp_path_factory):
    """Session-shared compiled recipe lowerings.

    Hands back a thin wrapper over ``analysis.core.get_lowering`` — the
    memoized lower+compile sweep over the shardlint RECIPES — so
    everything that needs a recipe's HLO (test_shardlint's detector
    fences, test_comms' and test_memory's ledger parity checks) pays one
    compile per step for the whole session instead of one per test.
    Threshold variations and ledger extraction are pure functions of the
    cached Lowering record.

    On first build per step the wrapper also drops the compiled artifacts
    (HLO text + measured peak/mesh/arg-classes JSON) under the session
    tmp dir — ``<name>.hlo`` / ``<name>.json`` in ``wrapper.cache_dir``
    — so subprocess consumers (the obs_memory CLI test) and pure-text
    re-analyses read files instead of recompiling.  ``wrapper.
    compile_count()`` exposes the process-wide AOT compile counter for
    the zero-extra-compiles asserts."""
    import json

    from pytorch_distributed_tpu.analysis import core
    from pytorch_distributed_tpu.obs import comms, memory

    cache_dir = tmp_path_factory.mktemp("hlo_cache")

    def wrapper(name: str):
        low = core.get_lowering(name)
        hlo_path = cache_dir / f"{name}.hlo"
        if not hlo_path.exists():
            hlo_path.write_text(low.text)
            (cache_dir / f"{name}.json").write_text(json.dumps({
                "name": name,
                "mesh_shape": low.mesh_shape,
                "measured_peak_bytes":
                    comms.compiled_peak_bytes(low.compiled),
                "arg_classes": memory.arg_classes_of(low.args),
            }))
        return low

    wrapper.cache_dir = cache_dir
    wrapper.compile_count = core.compile_count
    return wrapper
