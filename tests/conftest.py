"""Test configuration: simulate an 8-device TPU mesh on CPU.

Multi-device DP semantics (gradient psum, sharded batches, set_epoch
reshuffle) are testable with no TPU and no cluster via XLA's host-platform
device-count override — the test strategy SURVEY.md §4 prescribes for the
framework (the reference itself has no tests).

This container pre-imports jax in every process (a sitecustomize on
PYTHONPATH registers the tunneled-TPU "axon" PJRT plugin and sets
JAX_PLATFORMS=axon), so plain env-before-import doesn't work here.  Backends
initialize lazily, though, so overriding the config *after* import but before
first device use reliably lands the tests on the simulated CPU mesh.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402  (may already be imported by sitecustomize)

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

# NOTE on suite runtime: the suite is compile-dominated and serialized on
# the 1-core CI host.  jax's persistent compilation cache
# (jax_compilation_cache_dir) was tried here and REVERTED: on this
# jax/jaxlib (0.4.37, CPU backend) re-executing a deserialized cached
# executable aborts the process ("Fatal Python error: Aborted" in
# test_trainer's train step).  The re-attempt now lives behind
# ``analysis.lowering.maybe_enable_persistent_cache`` (called below):
# known-bad jaxlibs (< 0.5.0) short-circuit to disabled without probing,
# and on newer jaxlibs the cache auto-enables only after a populate+warm
# subprocess round-trip self-check passes — the abort cannot be
# try/except'd in-process, so only a subprocess can prove it safe.

# Installs the jax API compat shims (jax.shard_map / lax.axis_size on
# 0.4.x) before any test module does ``from jax import shard_map``.
import pytorch_distributed_tpu  # noqa: E402,F401

from pytorch_distributed_tpu.analysis import lowering as _lowering  # noqa: E402

_PERSISTENT_CACHE = _lowering.maybe_enable_persistent_cache()

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def lm_world32():
    """Session-shared tiny-LM training world: the full-device data mesh,
    the vocab-32 1-layer TransformerLM, and its synthetic dataset.

    Several suites fit this identical configuration (test_zero's parity
    and kill-resume drills, and anything else on the vocab-32 smoke
    model); sharing the objects keeps model.init traced once and — more
    importantly — lets fitted-trainer fixtures below amortize whole
    train-step compiles across tests on the 1-core CI host."""
    from pytorch_distributed_tpu.models.transformer import TransformerLM
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
    from pytorch_distributed_tpu.train.lm import SyntheticTokenDataset

    mesh = build_mesh(MeshSpec(("data",), (jax.device_count(),)))
    model = TransformerLM(vocab_size=32, d_model=32, n_heads=2, n_layers=1)
    ds = SyntheticTokenDataset(64, 16, 32)
    return mesh, model, ds


@pytest.fixture(scope="session")
def lm_wus_ref_fit(lm_world32):
    """The uninterrupted ``--zero wus`` reference run (8 steps, lr 0.05,
    batch 8): one compile + one fit for every test that needs the wus
    baseline (replicated-parity fences, kill-and-resume parity).  Tests
    must treat the returned trainer as read-only."""
    from pytorch_distributed_tpu.train.lm import LMTrainer

    mesh, model, ds = lm_world32
    with mesh:
        t = LMTrainer(model, mesh, ds, batch_size=8, lr=0.05,
                      eval_dataset=None, zero="wus")
        loss = t.fit(8, print_freq=4)
    return t, loss


@pytest.fixture(scope="session")
def get_lowering(tmp_path_factory):
    """Session-shared compiled recipe lowerings.

    Hands back a thin wrapper over ``analysis.core.get_lowering`` — the
    memoized lower+compile sweep over the shardlint RECIPES — so
    everything that needs a recipe's HLO (test_shardlint's detector
    fences, test_comms' and test_memory's ledger parity checks) pays one
    compile per step for the whole session instead of one per test.
    Threshold variations and ledger extraction are pure functions of the
    cached Lowering record.

    The sweep and its on-disk artifact layout are owned by the first-class
    service (``analysis.lowering.LoweringService``): on first build per
    step the service drops ``<name>.hlo`` / ``<name>.json`` (HLO text +
    measured peak/mesh/arg-classes) under ``wrapper.cache_dir`` so
    subprocess consumers (the obs_memory CLI test) and pure-text
    re-analyses read files instead of recompiling.  ``wrapper.
    compile_count()`` exposes the process-wide AOT compile counter for
    the zero-extra-compiles asserts, and ``wrapper.service`` the
    underlying LoweringService (``.load(name)`` for the no-jax disk
    view)."""
    from pytorch_distributed_tpu.analysis import lowering

    cache_dir = tmp_path_factory.mktemp("hlo_cache")
    svc = lowering.service(str(cache_dir))

    def wrapper(name: str):
        return svc.get(name)

    wrapper.cache_dir = cache_dir
    wrapper.compile_count = lowering.compile_count
    wrapper.compile_budget = lowering.compile_budget
    wrapper.service = svc
    return wrapper
