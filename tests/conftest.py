"""Test configuration: simulate an 8-device TPU mesh on CPU.

Multi-device DP semantics (gradient psum, sharded batches, set_epoch
reshuffle) are testable with no TPU and no cluster via XLA's host-platform
device-count override — the test strategy SURVEY.md §4 prescribes for the
framework (the reference itself has no tests).

This container pre-imports jax in every process (a sitecustomize on
PYTHONPATH registers the tunneled-TPU "axon" PJRT plugin and sets
JAX_PLATFORMS=axon), so plain env-before-import doesn't work here.  Backends
initialize lazily, though, so overriding the config *after* import but before
first device use reliably lands the tests on the simulated CPU mesh.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402  (may already be imported by sitecustomize)

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

# NOTE on suite runtime: the suite is compile-dominated and serialized on
# the 1-core CI host.  jax's persistent compilation cache
# (jax_compilation_cache_dir) was tried here and REVERTED: on this
# jax/jaxlib (0.4.37, CPU backend) re-executing a deserialized cached
# executable aborts the process ("Fatal Python error: Aborted" in
# test_trainer's train step).  Don't re-enable without upgrading jaxlib
# and re-running the full suite twice (populate + warm) to completion.

# Installs the jax API compat shims (jax.shard_map / lax.axis_size on
# 0.4.x) before any test module does ``from jax import shard_map``.
import pytorch_distributed_tpu  # noqa: E402,F401

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def get_lowering():
    """Session-shared compiled recipe lowerings.

    Hands back ``analysis.core.get_lowering`` — the memoized
    lower+compile sweep over the shardlint RECIPES — so everything that
    needs a recipe's HLO (test_shardlint's detector fences, test_comms'
    ledger parity checks) pays one compile per step for the whole
    session instead of one per test.  Threshold variations and ledger
    extraction are pure functions of the cached Lowering record.
    """
    from pytorch_distributed_tpu.analysis import core

    return core.get_lowering
