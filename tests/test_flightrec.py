"""Flight recorder + postmortem analyzer (ISSUE 13).

Tier-1 (no mesh, or one tiny compile): the ring/dump/watchdog/signal
contracts of ``obs/flightrec.py``, the checked-in 2-rank postmortem
fixture under ``tests/data/postmortem/``, and the trainer's
unhandled-exception death path.  The live 2-process hang test at the
bottom is ``slow``-marked: it reproduces the fixture's story end-to-end
(one rank stalls before the collective, the peer's watchdog fires inside
it, the analyzer names the stalled rank) across real processes.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from pytorch_distributed_tpu.obs import flightrec
from pytorch_distributed_tpu.obs.flightrec import (
    FlightRecorder,
    FlightSignalDump,
    HangWatchdog,
    attach_to_metrics,
    dump_path,
    find_dumps,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "data", "postmortem")


def _postmortem():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import postmortem

    return postmortem


# ------------------------------------------------------------- the ring --

def test_ring_is_bounded_and_counts_drops(tmp_path):
    rec = FlightRecorder(str(tmp_path), rank=3, capacity=8)
    for i in range(20):
        rec.record("tick", i, n=i)
    snap = rec.snapshot("test")
    assert snap["rank"] == 3
    assert snap["events_total"] == 20
    assert snap["events_dropped"] == 12
    assert len(snap["events"]) == 8
    # oldest events fell off the front; the tail is intact and ordered
    assert [e["step"] for e in snap["events"]] == list(range(12, 20))
    assert snap["events"][-1]["n"] == 19


def test_step_and_collective_window_scalars(tmp_path):
    rec = FlightRecorder(str(tmp_path))
    assert rec.in_step() is None
    rec.step_begin(5)
    rec.coll_enter(5, kind="all-reduce", bytes=4096.0)
    cur = rec.in_step()
    assert cur is not None and cur[0] == 5 and cur[1] >= 0.0
    assert rec.last_collective["kind"] == "all-reduce"
    assert rec.last_collective["step"] == 5
    rec.coll_exit(5)
    rec.step_end(5)
    assert rec.in_step() is None          # step_end clears the window
    # quantiles need 5 samples; below that the watchdog uses its floor
    assert rec.step_time_quantile(0.95) is None
    for s in range(6):
        rec.step_begin(10 + s)
        rec.step_end(10 + s, dt=0.1)
    assert rec.step_time_quantile(0.95) == pytest.approx(0.1)


def test_dump_is_atomic_json_and_never_raises(tmp_path):
    rec = FlightRecorder(str(tmp_path), rank=1)
    # non-JSON field (stand-in for a device scalar): default=str absorbs it
    rec.record("weird", 0, obj=object())
    path = rec.dump("test-reason")
    assert path == dump_path(str(tmp_path), 1)
    doc = json.loads(open(path).read())
    assert doc["reason"] == "test-reason"
    assert doc["events"][0]["kind"] == "weird"
    # atomic replace leaves no tmp litter next to the dump
    assert not [n for n in os.listdir(tmp_path) if ".tmp" in n]
    assert find_dumps(str(tmp_path)) == {1: path}
    # out_dir shadowed by a file: dump swallows the OSError, returns None
    blocked = FlightRecorder(str(tmp_path / "f" / "sub"))
    (tmp_path / "f").write_text("not a dir")
    assert blocked.dump("x") is None


def test_find_dumps_filters_and_survives_missing_dir(tmp_path):
    assert find_dumps(str(tmp_path / "missing")) == {}
    (tmp_path / "flightrec_rank7.json").write_text("{}")
    (tmp_path / "flightrec_rankX.json").write_text("{}")
    (tmp_path / "heartbeat-1.jsonl").write_text("")
    assert list(find_dumps(str(tmp_path))) == [7]


# -------------------------------------------------- signal-dump chaining --

def test_signal_dump_chains_to_previous_handler(tmp_path):
    rec = FlightRecorder(str(tmp_path), rank=0)
    hits = []
    prev = signal.signal(signal.SIGUSR1, lambda s, f: hits.append(s))
    fsd = FlightSignalDump(rec, signals=(signal.SIGUSR1,)).install()
    try:
        os.kill(os.getpid(), signal.SIGUSR1)
        # dump written with the signal reason, then chained onward
        doc = json.loads(open(dump_path(str(tmp_path), 0)).read())
        assert doc["reason"] == f"signal:{int(signal.SIGUSR1)}"
        assert doc["events"][-1]["kind"] == "signal"
        assert hits == [signal.SIGUSR1]
    finally:
        fsd.uninstall()
        restored = signal.getsignal(signal.SIGUSR1)
        signal.signal(signal.SIGUSR1, prev)
    # uninstall put the pre-install handler back
    assert getattr(restored, "__name__", None) == "<lambda>"


# ------------------------------------------------------------- watchdog --

def test_watchdog_fires_once_per_stall_and_rearms(tmp_path):
    rec = FlightRecorder(str(tmp_path))
    wd = HangWatchdog(rec, timeout=0.5, k=4.0)
    assert wd.threshold() == 0.5          # no samples yet: fixed floor
    rec.step_begin(2)
    rec.coll_enter(2, kind="all-gather")
    assert wd.check(now_elapsed=(2, 0.1)) is False   # under threshold
    assert wd.check(now_elapsed=(2, 1.0)) is True    # fires
    assert wd.check(now_elapsed=(2, 5.0)) is False   # latched on step 2
    assert wd.hangs == 1
    assert wd.check(now_elapsed=(3, 1.0)) is True    # new step re-arms
    assert wd.hangs == 2
    doc = json.loads(open(dump_path(str(tmp_path), 0)).read())
    assert doc["reason"] == "hang"
    hang = [e for e in doc["events"] if e["kind"] == "hang"][0]
    assert hang["collective"] == "all-gather"
    # with >=5 completed steps the threshold tracks k x p95
    for s in range(6):
        rec.step_begin(10 + s)
        rec.step_end(10 + s, dt=0.2)
    assert wd.threshold() == pytest.approx(0.8)      # 4 x 0.2 > 0.5 floor


def test_watchdog_daemon_thread_flags_a_live_stall(tmp_path):
    rec = FlightRecorder(str(tmp_path))

    class _Obs:
        def __init__(self):
            self.events = []

        def log_event(self, kind, step=None, **fields):
            self.events.append((kind, step, fields))

    obs = _Obs()
    wd = HangWatchdog(rec, obs=obs, timeout=0.05, poll_s=0.01).start()
    try:
        rec.step_begin(4)
        deadline = time.time() + 5.0
        while wd.hangs < 1 and time.time() < deadline:
            time.sleep(0.01)
        assert wd.hangs == 1
        time.sleep(0.1)                   # latch holds while stalled
        assert wd.hangs == 1
    finally:
        wd.stop()
    assert obs.events and obs.events[0][0] == "hang"
    assert obs.events[0][1] == 4
    assert os.path.exists(dump_path(str(tmp_path), 0))


def test_attach_to_metrics_mirrors_ft_events(tmp_path):
    rec = FlightRecorder(str(tmp_path))

    class _Obs:
        def __init__(self):
            self.calls = []

        def log_event(self, kind, step=None, **fields):
            self.calls.append((kind, step, fields))

    obs = _Obs()
    attach_to_metrics(rec, obs)
    obs.log_event("skip", step=3, reason="nonfinite")
    obs.log_event("hang", step=4, elapsed_s=9.0)     # watchdog-owned: skipped
    kinds = [(t, kind, step) for (t, kind, step, _) in rec._ring]
    assert ("skip") in [k[1] for k in kinds]
    assert "hang" not in [k[1] for k in kinds]
    # the original logger still saw both
    assert [c[0] for c in obs.calls] == ["skip", "hang"]


# --------------------------------------------- cross-rank merge (fixture) --

def test_checked_in_fixture_yields_root_cause():
    """The deterministic 2-rank story: rank 0 blocks inside the step-5
    all-reduce (its watchdog fires) because rank 1 stalled one collective
    behind with a +2 s clock skew — the analyzer must blame rank 1."""
    pm = _postmortem()
    report = pm.postmortem(FIXTURE)
    assert report["n_ranks"] == 2
    assert report["ranks"][1]["clock_offset_s"] == pytest.approx(2.0, abs=0.25)
    assert report["frontier_desync"] is True
    assert report["ranks"][0]["frontier"]["step"] == 5
    assert report["ranks"][1]["frontier"]["step"] == 4
    assert report["hang_ranks"] == [0]
    assert report["stalled_rank"] == 1
    assert report["step_skew"] == 0
    assert "rank 1 stalled first" in report["verdict"]
    assert "all-reduce@step 4" in report["verdict"]
    text = pm.render_text(report)
    assert "== postmortem ==" in text
    assert "<-- stalled first" in text
    json.dumps(report)                    # wire-clean for obs_report --json


def test_postmortem_degrades_on_empty_dir(tmp_path):
    pm = _postmortem()
    report = pm.postmortem(str(tmp_path))
    assert report["n_ranks"] == 0
    assert "no flight dumps" in report["verdict"]


def test_postmortem_cli_selftest():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "postmortem.py"),
         "--selftest"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ----------------------------------------- trainer death-path integration --

def test_trainer_dumps_on_unhandled_exception(tmp_path, lm_world32):
    """The exception death path end-to-end: a chaos injector blows up
    mid-fit and the rank's ring must land on disk with the exception
    reason before the error propagates."""
    from pytorch_distributed_tpu.ft.chaos import ChaosInjector, ChaosSchedule
    from pytorch_distributed_tpu.train.lm import LMTrainer

    class _Boom(ChaosInjector):
        def on_step(self, trainer, step):
            if step == 1:
                raise RuntimeError("chaos boom")

    mesh, model, ds = lm_world32
    with mesh:
        t = LMTrainer(model, mesh, ds, batch_size=8, lr=0.05, seed=0,
                      eval_dataset=None, prefetch=0,
                      chaos=ChaosSchedule(_Boom()),
                      flight_rec=str(tmp_path), hang_timeout=300.0)
        with pytest.raises(RuntimeError, match="chaos boom"):
            t.fit(4, print_freq=2)
    doc = json.loads(open(dump_path(str(tmp_path), 0)).read())
    assert doc["reason"] == "exception:RuntimeError"
    exc = [e for e in doc["events"] if e["kind"] == "exception"]
    assert exc and exc[0]["error"] == "RuntimeError"
    # step 0 completed before the blast; the ring shows its full window
    kinds = [e["kind"] for e in doc["events"]]
    assert {"step_begin", "coll_enter", "coll_exit", "step_end"} <= set(kinds)
    assert doc["membership"]["world"] == dict(mesh.shape)["data"]


# ------------------------------------------------ live 2-process story --

_HANG_WORKER = textwrap.dedent(
    """
    import os, signal, sys, time
    pid = sys.argv[1]
    out = sys.argv[2]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    os.environ["PTD_TPU_COORDINATOR"] = "127.0.0.1:%(port)d"
    os.environ["PTD_TPU_NUM_PROCESSES"] = "2"
    os.environ["PTD_TPU_PROCESS_ID"] = pid
    sys.path.insert(0, %(repo)r)
    import jax
    jax.config.update("jax_platforms", "cpu")
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh, initialize
    ctx = initialize()
    assert ctx.process_count == 2
    from pytorch_distributed_tpu.ft.chaos import ChaosInjector, ChaosSchedule
    from pytorch_distributed_tpu.models.transformer import TransformerLM
    from pytorch_distributed_tpu.train.lm import LMTrainer, SyntheticTokenDataset
    from pytorch_distributed_tpu.utils.preempt import PreemptionGuard

    STALL_STEP, STALL_S = 3, 4.0

    class LateRank(ChaosInjector):
        # on_step runs BEFORE flight.step_begin/coll_enter in the hot
        # loop, so the stalled rank's frontier stays one collective
        # behind its peer -- the postmortem's desync signature.  After
        # the stall it SIGTERMs itself: flight dump (signal:15), then
        # the chained PreemptionGuard stops both ranks at the next
        # agreement boundary.
        def on_step(self, trainer, step):
            if ctx.process_index == 1 and step == STALL_STEP:
                time.sleep(STALL_S)
                os.kill(os.getpid(), signal.SIGTERM)

    mesh = build_mesh(MeshSpec(("data",), (2,)))
    model = TransformerLM(vocab_size=32, d_model=32, n_heads=2, n_layers=1)
    ds = SyntheticTokenDataset(64, 16, 32)
    # Rank 0 keeps a tight watchdog (it is the one blocked inside the
    # collective); rank 1's is parked high so the step-0 compile stall
    # cannot fake a hang on the rank whose story is "stalled BEFORE the
    # collective".
    timeout = 1.0 if ctx.process_index == 0 else 300.0
    guard = PreemptionGuard().install()
    with mesh:
        t = LMTrainer(model, mesh, ds, batch_size=4, lr=0.05, seed=0,
                      is_primary=ctx.is_primary, preempt=guard,
                      prefetch=0, hb_dir=out, hb_interval_s=0.0,
                      flight_rec=out, hang_timeout=timeout,
                      chaos=ChaosSchedule(LateRank()))
        t.fit(12, print_freq=2)
    hangs = t._hang_wd.hangs if t._hang_wd is not None else 0
    print("FLIGHT", ctx.process_index, int(t.state.step), hangs, flush=True)
    """
)


@pytest.mark.slow
def test_two_process_hang_postmortem(tmp_path):
    """Live reproduction of the fixture story across 2 real processes:
    rank 1 stalls in on_step (before entering the step-3 collective),
    rank 0 blocks inside the psum and its watchdog dumps pre-mortem,
    rank 1 SIGTERMs itself (signal dump + preemption stop for both), and
    the merged postmortem names rank 1 via the desync frontier."""
    mp = __import__("test_multiprocess")
    out = tmp_path / "flight"
    out.mkdir()
    outs = mp._run_workers(tmp_path, _HANG_WORKER, 2, extra_args=[out])
    flight = {r: p.split() for r, p in mp._parse(outs, "FLIGHT").items()}
    assert set(flight) == {0, 1}, outs
    assert int(flight[0][1]) >= 1, outs   # rank 0's watchdog fired
    assert int(flight[1][1]) == 0, outs   # rank 1's never did

    dumps = find_dumps(str(out))
    assert set(dumps) == {0, 1}
    d0 = json.loads(open(dumps[0]).read())
    d1 = json.loads(open(dumps[1]).read())
    assert d0["reason"] == "hang"
    assert d1["reason"] == f"signal:{int(signal.SIGTERM)}"
    # frontier desync: rank 0 entered the step-3 collective, rank 1 never
    assert d0["in_step"] and d0["in_step"]["step"] == 3
    assert d0["last_collective"]["step"] == 3
    assert d1["last_collective"]["step"] == 2

    pm = _postmortem()
    report = pm.postmortem(str(out), hb_dir=str(out))
    assert report["n_ranks"] == 2
    assert report["hang_ranks"] == [0]
    assert report["frontier_desync"] is True
    assert report["stalled_rank"] == 1
    assert "rank 1 stalled first" in report["verdict"]
