"""Live telemetry plane: alert rules + per-rank metric export (ISSUE 14).

Tier-1 (no mesh): rule parsing/validation, per-kind engine semantics on
planted record streams (fire / latch / re-arm), the heartbeat and
bench-staleness legs, the emit round-trip through a real
``MetricsLogger`` JSONL (goodput + report folding), the HTTP exporter
round-trip over a real ephemeral socket, the recipe-flag lint, and the
``obs_live --selftest`` subprocess (which also proves the aggregator
stays jax-free).  The 2-process live-fleet test at the bottom is
``slow``-marked: two real rank processes export metrics, one dies, and
``obs_live --once`` must raise the step-time and dead-rank alerts
within two aggregation cycles and book them into the shared JSONL.
"""

import json
import os
import socket
import subprocess
import sys
import textwrap
import time
import urllib.request
from datetime import datetime, timedelta, timezone

import pytest

from pytorch_distributed_tpu.obs.alerts import (
    AlertEngine,
    AlertRuleError,
    RULE_KINDS,
    Rule,
    alerts_data,
    dead_ranks_from_events,
    default_rules,
    evaluate_stream,
    load_rules,
    summarize_alerts,
)
from pytorch_distributed_tpu.obs.export import (
    MetricsExporter,
    parse_prometheus,
    sample_value,
)
from pytorch_distributed_tpu.obs.goodput import compute_goodput
from pytorch_distributed_tpu.obs.metrics import MetricsLogger, read_metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OBS_LIVE = os.path.join(REPO, "scripts", "obs_live.py")


def step_rec(step, st=0.010, proc=0, t=None, **extra):
    """A minimal metrics record with uniform step-time quantiles."""
    rec = {"step": step, "t": time.time() if t is None else t,
           "process": proc, "step_time": st, "step_time_ema": st,
           "step_time_p50": st, "step_time_p95": st, "step_time_max": st}
    rec.update(extra)
    return rec


# --------------------------------------------------------------- the rules --

def test_load_rules_roundtrip(tmp_path):
    p = tmp_path / "rules.json"
    p.write_text(json.dumps({"rules": [
        {"kind": "step_time_p95", "name": "st", "severity": "page",
         "max_ms": 25.0, "quantile": "p50", "warmup_steps": 3},
        {"kind": "dead_rank", "max_age_s": 30.0},
        {"kind": "bench_stale", "max_days": 7.0, "lkg_path": "/x.json"},
    ]}))
    rules = load_rules(str(p))
    assert [(r.kind, r.name, r.severity) for r in rules] == [
        ("step_time_p95", "st", "page"), ("dead_rank", "dead_rank", "warn"),
        ("bench_stale", "bench_stale", "warn")]
    assert rules[0].params == {"max_ms": 25.0, "quantile": "p50",
                               "warmup_steps": 3}
    # a bare top-level list works too
    p.write_text(json.dumps([{"kind": "hang"}]))
    assert load_rules(str(p))[0].kind == "hang"


@pytest.mark.parametrize("payload,needle", [
    ([{"kind": "nope"}], "unknown kind"),
    ([{"kind": "step_time_p95"}], "max_ms"),
    ([{"kind": "hang", "max_ms": 1}], "unknown parameter"),
    ([{"kind": "hang", "severity": "fatal"}], "severity"),
    ([{"kind": "step_time_p95", "max_ms": 1, "quantile": "p99"}],
     "quantile"),
    ([{"kind": "step_time_p95", "max_ms": "fast"}], "number"),
    ([{"kind": "bench_stale", "max_days": 1, "lkg_path": 3}], "path"),
    ([{"kind": "hang"}, {"kind": "hang"}], "duplicate"),
    (["hang"], "expected an object"),
    ({"not_rules": []}, "expected"),
])
def test_malformed_rules_raise(tmp_path, payload, needle):
    p = tmp_path / "bad.json"
    p.write_text(json.dumps(payload))
    with pytest.raises(AlertRuleError) as ei:
        load_rules(str(p))
    assert needle in str(ei.value)


def test_unreadable_rules_raise(tmp_path):
    with pytest.raises(AlertRuleError, match="cannot read"):
        load_rules(str(tmp_path / "absent.json"))
    p = tmp_path / "garbage.json"
    p.write_text("{not json")
    with pytest.raises(AlertRuleError, match="not valid JSON"):
        load_rules(str(p))


def test_default_rules_are_valid_and_named_uniquely():
    rules = default_rules()
    names = [r.name for r in rules]
    assert len(names) == len(set(names))
    for r in rules:
        assert r.kind in RULE_KINDS
        assert r.severity in ("warn", "page")
    assert {r.kind for r in rules} >= {"dead_rank", "slow_rank", "hang",
                                       "recompile", "bench_stale"}


# -------------------------------------------------------------- the engine --

def test_step_time_rule_fires_latches_and_rearms():
    eng = AlertEngine([Rule("step_time_p95", "st", "page",
                            {"max_ms": 15.0, "warmup_steps": 2})])
    assert eng.observe(step_rec(0, st=0.050)) == []  # warmup suppresses
    assert eng.observe(step_rec(2)) == []            # under ceiling
    fired = eng.observe(step_rec(3, st=0.020))
    assert len(fired) == 1
    a = fired[0]
    assert (a.name, a.severity, a.step, a.rank) == ("st", "page", 3, 0)
    assert a.value == pytest.approx(20.0)
    assert a.threshold == 15.0
    assert "20.0ms > 15ms" in a.detail
    assert eng.observe(step_rec(4, st=0.030)) == []  # latched
    assert eng.active() and eng.active()[0].name == "st"
    assert eng.observe(step_rec(5)) == []            # recovery clears
    assert not eng.active()
    assert len(eng.observe(step_rec(6, st=0.020))) == 1  # re-armed
    assert len(eng.history) == 2


def test_step_time_quantile_selects_the_field():
    eng = AlertEngine([Rule("step_time_p95", "st", "warn",
                            {"max_ms": 15.0, "quantile": "p50",
                             "warmup_steps": 0})])
    rec = step_rec(5, st=0.010)
    rec["step_time_p50"] = 0.040  # only the chosen quantile breaches
    (a,) = eng.observe(rec)
    assert a.value == pytest.approx(40.0) and "p50" in a.detail


def test_step_time_latch_is_per_rank():
    eng = AlertEngine([Rule("step_time_p95", "st", "warn",
                            {"max_ms": 15.0, "warmup_steps": 0})])
    fired = eng.observe(step_rec(3, st=0.020, proc=0))
    fired += eng.observe(step_rec(3, st=0.020, proc=1))
    assert sorted(a.rank for a in fired) == [0, 1]
    assert eng.observe(step_rec(4, st=0.020, proc=1)) == []  # latched


def test_exposed_comm_and_mem_peak_rules():
    eng = AlertEngine([
        Rule("exposed_comm", "comm", "warn", {"max_ms": 2.0}),
        Rule("mem_peak", "mem", "page", {"max_bytes": 1 << 20}),
    ])
    assert eng.observe(step_rec(1, exposed_comm_ms=1.0,
                                mem_peak_bytes=1000)) == []
    fired = eng.observe(step_rec(2, exposed_comm_ms=3.5,
                                 mem_peak_bytes=2 << 20))
    assert {a.name for a in fired} == {"comm", "mem"}
    comm = next(a for a in fired if a.name == "comm")
    assert comm.value == pytest.approx(3.5) and comm.threshold == 2.0
    mem = next(a for a in fired if a.name == "mem")
    assert "MiB" in mem.detail
    # records without the fields leave both rules inert
    assert eng.observe(step_rec(3)) == []
    assert len(eng.active()) == 2  # still latched: no recovery signal yet


def test_goodput_floor_rule_needs_min_steps_then_fires():
    eng = AlertEngine([Rule("goodput_floor", "gp", "warn",
                            {"min_pct": 50.0, "min_steps": 5})])
    t0 = 1000.0
    fired = []
    for i in range(8):  # 0.2 s productive out of each 1 s of wall time
        fired += eng.observe(step_rec(i, st=0.2, t=t0 + i))
    assert len(fired) == 1
    assert fired[0].value < 50.0 and fired[0].threshold == 50.0


def test_hang_and_recompile_event_rules():
    eng = AlertEngine([Rule("hang", "hang", "page", {}),
                       Rule("recompile", "rc", "warn", {"max_events": 1})])
    (a,) = eng.observe({"ft_event": "hang", "step": 7, "process": 0,
                        "t": 1.0, "collective": "all-reduce",
                        "elapsed_s": 12.0})
    assert a.severity == "page" and "all-reduce" in a.detail
    assert eng.observe({"ft_event": "recompile", "step": 8, "t": 2.0,
                        "process": 0}) == []  # within budget
    (b,) = eng.observe({"ft_event": "recompile", "step": 9, "t": 3.0,
                        "process": 0})
    assert b.value == 2.0 and b.threshold == 1.0


def test_engine_never_alerts_on_alert_events():
    eng = AlertEngine(default_rules())
    assert eng.observe({"ft_event": "alert", "alert": "hang",
                        "rule": "hang", "t": 1.0, "process": 0}) == []
    assert not eng.active()


def test_dead_and_slow_rank_rules_over_heartbeats():
    now = time.time()
    beats = {
        0: {"pid": 0, "step": 20, "t": now, "ema": 0.010},
        1: {"pid": 1, "step": 20, "t": now - 120.0, "ema": 0.010},
        2: {"pid": 2, "step": 10, "t": now, "ema": 0.050},
        3: {"pid": 3, "step": 20, "t": now, "ema": 0.010},
    }
    eng = AlertEngine([
        Rule("dead_rank", "dead", "page", {"max_age_s": 60.0}),
        Rule("slow_rank", "slow", "warn",
             {"max_step_lag": 3, "slow_ema_factor": 2.0,
              "max_age_s": 60.0}),
    ])
    fired = eng.observe_heartbeats(beats, now=now)
    got = {(a.name, a.rank) for a in fired}
    assert got == {("dead", 1), ("slow", 2)}
    assert "dead or hung" in next(a for a in fired if a.name == "dead").detail
    # latched across cycles; recovery clears
    assert eng.observe_heartbeats(beats, now=now) == []
    beats[1]["t"] = now
    beats[2].update(step=20, ema=0.010)
    assert eng.observe_heartbeats(beats, now=now) == []
    assert not eng.active()


def test_bench_stale_rule(tmp_path):
    lkg = tmp_path / "BENCH_LKG.json"
    stamp = (datetime.now(timezone.utc)
             - timedelta(days=20)).strftime("%Y-%m-%dT%H:%M:%S%z")
    lkg.write_text(json.dumps({"metric": "tok/s", "value": 1.0,
                               "captured_at": stamp}))
    params = {"max_days": 14.0, "lkg_path": str(lkg),
              "events_path": str(tmp_path / "absent_events.jsonl")}
    eng = AlertEngine([Rule("bench_stale", "stale", "warn", dict(params))])
    (a,) = eng.check_bench()
    assert a.value == pytest.approx(20.0, abs=0.1) and a.threshold == 14.0
    # a fresh capture clears it
    lkg.write_text(json.dumps({"metric": "tok/s", "value": 1.0,
                               "captured_at": datetime.now(timezone.utc)
                               .strftime("%Y-%m-%dT%H:%M:%S%z")}))
    eng2 = AlertEngine([Rule("bench_stale", "stale", "warn", dict(params))])
    assert eng2.check_bench() == []


def test_evaluate_stream_one_shot():
    now = time.time()
    recs = ([step_rec(i) for i in range(5)]
            + [{"ft_event": "hang", "step": 5, "t": now, "process": 0}])
    beats = {0: {"pid": 0, "step": 5, "t": now - 300.0}}
    eng = evaluate_stream(recs, default_rules(), beats=beats, now=now)
    assert {a.kind for a in eng.history} == {"hang", "dead_rank"}


# ---------------------------------------------------------- emit round-trip --

def test_emit_books_alert_ft_events_that_every_fold_sees(tmp_path):
    mpath = tmp_path / "metrics.jsonl"
    log = MetricsLogger(str(mpath), flush_every=1)
    eng = AlertEngine([Rule("step_time_p95", "st", "warn",
                            {"max_ms": 50.0, "quantile": "p50",
                             "warmup_steps": 2})],
                      emit=lambda **f: log.log_event("alert", **f))
    log.register(eng)
    for i in range(6):
        log.log_step(i, 0.2)  # p50 200 ms > 50 ms after warmup
    log.close()

    records = read_metrics(str(mpath))
    events = [r for r in records if r.get("ft_event") == "alert"]
    assert len(events) == 1, "one breach episode → one booked alert"
    e = events[0]
    assert (e["alert"], e["rule"], e["severity"]) == \
        ("st", "step_time_p95", "warn")
    assert e["value"] > e["threshold"] == 50.0
    # the goodput ledger, the report section, and the JSON fold all see it
    assert compute_goodput(records).alerts == 1
    summary = "\n".join(summarize_alerts(records))
    assert "== alerts ==" in summary and "st" in summary
    data = alerts_data(records)
    assert data["total"] == 1 and data["by_name"]["st"]["count"] == 1


def test_emit_errors_never_reach_the_training_loop():
    def bomb(**_f):
        raise RuntimeError("sink exploded")

    eng = AlertEngine([Rule("step_time_p95", "st", "warn",
                            {"max_ms": 1.0, "warmup_steps": 0})],
                      emit=bomb)
    (a,) = eng.observe(step_rec(1, st=0.5))  # fired, emit swallowed
    assert a.name == "st"
    # evaluation errors are swallowed too once an emit is wired
    eng.observe({"step_time": "not-a-number", "step": 2})


def test_dead_ranks_from_events_respects_since_t():
    evs = [
        {"ft_event": "alert", "rule": "dead_rank", "rank": 1, "t": 10.0},
        {"ft_event": "alert", "rule": "dead_rank", "rank": 1, "t": 20.0},
        {"ft_event": "alert", "rule": "dead_rank", "rank": 2, "t": 5.0},
        {"ft_event": "alert", "rule": "slow_rank", "rank": 3, "t": 30.0},
    ]
    assert dead_ranks_from_events(evs) == {1: 20.0, 2: 5.0}
    assert dead_ranks_from_events(evs, since_t=10.0) == {1: 20.0}
    assert dead_ranks_from_events(evs, since_t=25.0) == {}


# -------------------------------------------------------------- the export --

def test_exporter_http_roundtrip_on_ephemeral_port():
    eng = AlertEngine([Rule("step_time_p95", "st", "page",
                            {"max_ms": 15.0, "warmup_steps": 0})])
    eng.observe(step_rec(41, st=0.020, proc=7))
    exp = MetricsExporter(0, rank=7, engine=eng)
    exp.update(step_rec(41, st=0.020, proc=7, throughput=51200.0,
                        loss=2.5))
    exp.update({"ft_event": "rollback", "t": time.time(), "process": 7})
    exp.update({"ft_event": "alert", "t": time.time(), "process": 7,
                "alert": "st", "rule": "step_time_p95"})
    exp.start()
    try:
        assert exp.port != 0, "port 0 must resolve to the bound port"
        base = f"http://127.0.0.1:{exp.port}"
        with urllib.request.urlopen(f"{base}/metrics", timeout=2.0) as r:
            assert r.status == 200
            samples = parse_prometheus(r.read().decode())
        assert sample_value(samples, "ptd_up", rank=7) == 1.0
        assert sample_value(samples, "ptd_step", rank=7) == 41.0
        assert sample_value(samples, "ptd_step_time_seconds", rank=7,
                            stat="last") == pytest.approx(0.020)
        assert sample_value(samples, "ptd_metric", rank=7,
                            field="loss") == 2.5
        assert sample_value(samples, "ptd_metric", rank=7,
                            field="throughput") == 51200.0
        assert sample_value(samples, "ptd_ft_events_total", rank=7,
                            kind="rollback") == 1.0
        assert sample_value(samples, "ptd_alerts_total", rank=7) == 1.0
        assert sample_value(samples, "ptd_alert_firing", rank=7,
                            rule="st", severity="page") == 1.0
        with urllib.request.urlopen(f"{base}/healthz", timeout=2.0) as r:
            health = json.loads(r.read())
        assert health["ok"] is True and health["rank"] == 7
        try:
            urllib.request.urlopen(f"{base}/nope", timeout=2.0)
        except urllib.error.HTTPError as e:
            assert e.code == 404
        else:
            raise AssertionError("unknown path must 404")
    finally:
        exp.stop()
    exp.stop()  # idempotent


def test_exporter_healthz_503_before_first_record():
    exp = MetricsExporter(0, rank=0)
    exp.start()
    try:
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{exp.port}/healthz", timeout=2.0)
        except urllib.error.HTTPError as e:
            assert e.code == 503
        else:
            raise AssertionError("no record yet must read not-ok")
    finally:
        exp.stop()


def test_parse_prometheus_handles_quoted_labels():
    text = ('ptd_metric{rank="0",field="a,b"} 1.5\n'
            '# a comment\n'
            'ptd_up{rank="0"} 1\n'
            'garbage line without a value\n')
    samples = parse_prometheus(text)
    assert ("ptd_metric", {"rank": "0", "field": "a,b"}, 1.5) in samples
    assert sample_value(samples, "ptd_up", rank=0) == 1.0


def test_exporter_is_a_metrics_logger_sink(tmp_path):
    """Registered twice (lifecycle + per-record), the exporter serves the
    latest drained record with zero work in ``log_step`` itself."""
    log = MetricsLogger(str(tmp_path / "m.jsonl"), flush_every=1)
    exp = MetricsExporter(0, rank=0)
    log.register(exp)          # start/stop pair → started here
    log.register(exp.update)   # callable → per-record sink
    assert exp.running
    log.log_step(3, 0.01)
    samples = parse_prometheus(exp.render())
    assert sample_value(samples, "ptd_step", rank=0) == 3.0
    log.close()
    assert not exp.running, "close() must stop the owned exporter"


# ------------------------------------------------------------- the CLI leg --

def test_obs_live_selftest_subprocess():
    """The aggregator's own checks pass in a clean process — including
    its assertion that jax never gets imported."""
    proc = subprocess.run([sys.executable, OBS_LIVE, "--selftest"],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "obs_live selftest: OK" in proc.stdout


# --------------------------------------------------- the live fleet (slow) --

_DRIVER = textwrap.dedent("""\
    import argparse, importlib.util, json, os, sys, time

    def load(name):
        alias = f"_ptd_obs_{name}"
        if alias in sys.modules:
            return sys.modules[alias]
        spec = importlib.util.spec_from_file_location(
            alias, os.path.join(OBS, f"{name}.py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[alias] = mod
        spec.loader.exec_module(mod)
        return mod

    ap = argparse.ArgumentParser()
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--hb-dir", required=True)
    ap.add_argument("--out", required=True)
    ap.add_argument("--rules", required=True)
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--step-time", type=float, default=0.1)
    ap.add_argument("--die-at", type=int, default=None)
    ap.add_argument("--linger", type=float, default=30.0)
    args = ap.parse_args()

    OBS = os.environ["PTD_OBS_DIR"]
    metrics = load("metrics"); heartbeat = load("heartbeat")
    export = load("export"); alerts = load("alerts")
    assert "jax" not in sys.modules

    log = metrics.MetricsLogger(args.out, process_index=args.rank,
                                flush_every=1)
    eng = alerts.AlertEngine(alerts.load_rules(args.rules),
                             emit=lambda **f: log.log_event("alert", **f),
                             process_index=args.rank)
    eng._bench_checked = True  # no bench anchor in this fleet
    exp = export.MetricsExporter(args.port, rank=args.rank, engine=eng)
    log.register(exp); log.register(exp.update); log.register(eng)
    hb = heartbeat.HeartbeatWriter(args.hb_dir, args.rank, interval_s=0.0,
                                   world=2)
    print(f"rank {args.rank} ready on :{exp.port}", flush=True)
    for step in range(args.steps):
        time.sleep(args.step_time)
        log.log_step(step, args.step_time)
        hb.beat(step, step_time_ema=log.ema)
        if args.die_at is not None and step >= args.die_at:
            os._exit(1)  # no close(), no final beat: a real death
    log.close()
    time.sleep(args.linger)
""")


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


@pytest.mark.slow
def test_live_fleet_alerts_within_two_cycles(tmp_path):
    """Two real rank processes: rank 1 dies mid-run, rank 0 drags every
    step past the rule ceiling.  ``obs_live --once`` (the aggregation
    cycle) must surface both alerts within two cycles, exit 1, and book
    the dead rank into the shared JSONL that goodput/obs_report fold."""
    hb = tmp_path / "hb"
    rules = tmp_path / "rules.json"
    rules.write_text(json.dumps({"rules": [
        {"kind": "step_time_p95", "name": "step_time", "severity": "warn",
         "quantile": "p50", "max_ms": 50.0, "warmup_steps": 3},
        {"kind": "dead_rank", "severity": "page", "max_age_s": 2.0},
    ]}))
    driver = tmp_path / "driver.py"
    driver.write_text(_DRIVER)
    env = dict(os.environ, PTD_OBS_DIR=os.path.join(
        REPO, "pytorch_distributed_tpu", "obs"))
    ports = _free_ports(2)
    outs = [str(tmp_path / f"metrics-{r}.jsonl") for r in (0, 1)]
    procs = []
    try:
        for rank, die in ((0, None), (1, 6)):
            cmd = [sys.executable, str(driver), "--rank", str(rank),
                   "--port", str(ports[rank]), "--hb-dir", str(hb),
                   "--out", outs[rank], "--rules", str(rules),
                   "--steps", "60", "--step-time", "0.1"]
            if die is not None:
                cmd += ["--die-at", str(die)]
            procs.append(subprocess.Popen(cmd, env=env,
                                          stdout=subprocess.PIPE,
                                          stderr=subprocess.STDOUT,
                                          text=True))
        deadline = time.time() + 30.0
        while procs[1].poll() is None and time.time() < deadline:
            time.sleep(0.2)
        assert procs[1].poll() is not None, "rank 1 never died"
        time.sleep(2.5)  # let rank 1's last beat age past max_age_s

        booked = str(tmp_path / "aggregated.jsonl")
        cycles = 0
        for cycles in (1, 2):  # "within two aggregation cycles"
            once = subprocess.run(
                [sys.executable, OBS_LIVE, "--ports", str(ports[0]),
                 "--world", "1", "--hb-dir", str(hb), "--rules",
                 str(rules), "--alerts-jsonl", booked, "--once"],
                capture_output=True, text=True, timeout=60)
            if once.returncode == 1 and "dead_rank" in once.stdout \
                    and "step_time" in once.stdout:
                break
        else:
            raise AssertionError(
                f"alerts not firing after {cycles} cycles:\n{once.stdout}"
                f"\n{once.stderr}")

        # the aggregator booked the death rank 1 could never book itself
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.wait(timeout=10)

    agg = read_metrics(booked)
    assert 1 in dead_ranks_from_events(agg), \
        "obs_live must book the dead_rank alert into the shared JSONL"

    # rank 0's own engine booked the step-time breach live
    r0 = read_metrics(outs[0])
    mine = [e for e in r0 if e.get("ft_event") == "alert"]
    assert any(e["rule"] == "step_time_p95" for e in mine), mine
    # and every fold sees the combined story
    combined = r0 + agg
    assert compute_goodput(combined).alerts >= 2
    summary = "\n".join(summarize_alerts(combined))
    assert "step_time" in summary and "dead_rank" in summary
    rep = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "obs_report.py"),
         "--metrics-jsonl", outs[0]],
        capture_output=True, text=True, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert "== alerts ==" in rep.stdout, rep.stdout + rep.stderr
