"""FSDP/ZeRO-3 layout: sharded params+opt state, replicated-parity step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from pytorch_distributed_tpu.models.transformer import TransformerLM
from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
from pytorch_distributed_tpu.parallel.fsdp import fsdp_specs
from pytorch_distributed_tpu.parallel.tp import tp_specs
from pytorch_distributed_tpu.train.lm import make_lm_train_step
from pytorch_distributed_tpu.train.optim import sgd_init
from pytorch_distributed_tpu.train.state import TrainState

VOCAB, D, HEADS, SEQ, BATCH = 64, 32, 2, 32, 8


def _setup(mesh, specs):
    from pytorch_distributed_tpu.parallel.tp import shard_state

    model = TransformerLM(vocab_size=VOCAB, d_model=D, n_heads=HEADS,
                          n_layers=2)
    tokens0 = jnp.zeros((1, SEQ), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens0)["params"]
    sp = specs(params) if callable(specs) else specs
    state = shard_state(
        TrainState.create({"params": params}, sgd_init(params)), sp, mesh)
    return model, state, sp


def test_fsdp_step_matches_replicated():
    from jax.sharding import NamedSharding

    mesh = build_mesh(MeshSpec(("data",), (8,)), jax.devices()[:8])
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, VOCAB, size=(BATCH, SEQ))
                         .astype(np.int32))
    with mesh:
        model, s_rep, _ = _setup(mesh, lambda p: jax.tree_util.tree_map(
            lambda _: P(), p))
        step_rep = make_lm_train_step(
            model, mesh, jax.tree_util.tree_map(lambda _: P(), s_rep.params),
            weight_decay=0.0)
        toks = jax.device_put(tokens, NamedSharding(mesh, P("data", None)))
        s_rep2, m_rep = step_rep(s_rep, toks, jnp.float32(0.05))

        model, s_fsdp, sp = _setup(
            mesh, lambda p: fsdp_specs(p, mesh))
        step_fsdp = make_lm_train_step(model, mesh, sp, weight_decay=0.0)
        s_fsdp2, m_fsdp = step_fsdp(s_fsdp, toks, jnp.float32(0.05))

    assert float(m_rep["loss"]) == pytest.approx(float(m_fsdp["loss"]),
                                                 rel=2e-5)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(s_rep2.params)),
                    jax.tree_util.tree_leaves(jax.device_get(s_fsdp2.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_fsdp_actually_shards_memory():
    mesh = build_mesh(MeshSpec(("data",), (8,)), jax.devices()[:8])
    with mesh:
        model, state, sp = _setup(mesh, lambda p: fsdp_specs(p, mesh))
    embed = state.params["embed"]["embedding"]
    shard = embed.addressable_shards[0].data
    assert shard.size * 8 == embed.size  # 1/8th per device
    # momentum (optimizer state) shares the layout — the ZeRO part
    mom = state.momentum["embed"]["embedding"]
    assert mom.addressable_shards[0].data.size * 8 == mom.size
    # tiny leaves stay replicated
    ln = state.params["block_0"]["ln1"]["scale"]
    assert ln.addressable_shards[0].data.size == ln.size


def test_fsdp_composes_with_tp():
    mesh = build_mesh(MeshSpec(("data", "model"), (4, 2)), jax.devices()[:8])
    model = TransformerLM(vocab_size=VOCAB, d_model=D, n_heads=HEADS,
                          n_layers=1)
    params = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), jnp.zeros((1, SEQ), jnp.int32))
    )["params"]
    base = tp_specs(params)
    sp = fsdp_specs(params, mesh, base_specs=base)
    qkv = sp["block_0"]["attn"]["qkv"]["kernel"]
    # column-parallel model axis kept; the free dim gains the data axis
    assert "model" in qkv and "data" in qkv


def test_fsdp_composes_with_ep():
    """--fsdp with --ep: expert-stacked leaves keep P('expert', ...) and
    gain the data axis on a free dim; one step matches the plain-EP layout."""
    from jax.sharding import NamedSharding

    from pytorch_distributed_tpu.models.moe import moe_specs
    from pytorch_distributed_tpu.parallel.tp import shard_state

    mesh = build_mesh(MeshSpec(("data", "expert"), (4, 2)), jax.devices()[:8])
    model = TransformerLM(vocab_size=VOCAB, d_model=D, n_heads=HEADS,
                          n_layers=1, moe_experts=2)
    tokens0 = jnp.zeros((1, SEQ), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens0)["params"]

    base = moe_specs(params)
    sp = fsdp_specs(params, mesh, base_specs=base, min_size=64)
    fc1 = sp["block_0"]["moe"]["experts"]["fc1"]["kernel"]
    assert "expert" in fc1 and "data" in fc1
    assert sp != base, "fsdp_specs left the ep layout unchanged"

    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, VOCAB, size=(BATCH, SEQ))
                         .astype(np.int32))
    results = {}
    with mesh:
        toks = jax.device_put(tokens, NamedSharding(mesh, P("data", None)))
        for name, specs in (("ep", base), ("ep_fsdp", sp)):
            # fresh copy per layout: shard_state donates (deletes) its input
            p = jax.tree_util.tree_map(jnp.array, params)
            state = shard_state(
                TrainState.create({"params": p}, sgd_init(p)), specs, mesh)
            step = make_lm_train_step(model, mesh, specs, weight_decay=0.0)
            state2, metrics = step(state, toks, jnp.float32(0.05))
            results[name] = (float(metrics["loss"]),
                             jax.device_get(state2.params))
    assert results["ep"][0] == pytest.approx(results["ep_fsdp"][0], rel=2e-5)
    for a, b in zip(jax.tree_util.tree_leaves(results["ep"][1]),
                    jax.tree_util.tree_leaves(results["ep_fsdp"][1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_lm_pretrain_ep_fsdp_runs_and_learns(capsys, tmp_path):
    from pytorch_distributed_tpu.recipes import lm_pretrain

    final = lm_pretrain.main([
        "--vocab", "32", "--d-model", "32", "--n-heads", "2",
        "--n-layers", "1", "--seq-len", "32", "-b", "8",
        "--steps", "15", "--lr", "0.05", "-p", "4",
        "--dataset-length", "8", "--precision", "fp32",
        "--ep", "2", "--fsdp", "--no-eval",
        "--checkpoint-dir", str(tmp_path),
    ])
    out = capsys.readouterr().out
    first = float(out.split("Loss ")[1].split(" ")[0])
    assert final < first
    assert (tmp_path / "checkpoint.msgpack").exists()


def test_lm_pretrain_fsdp_runs_and_learns(capsys, tmp_path):
    from pytorch_distributed_tpu.recipes import lm_pretrain

    final = lm_pretrain.main([
        "--vocab", "32", "--d-model", "32", "--n-heads", "2",
        "--n-layers", "1", "--seq-len", "32", "-b", "8",
        "--steps", "15", "--lr", "0.05", "-p", "4",
        "--dataset-length", "8", "--precision", "fp32",
        "--fsdp", "--no-eval",
        "--checkpoint-dir", str(tmp_path),
    ])
    out = capsys.readouterr().out
    first = float(out.split("Loss ")[1].split(" ")[0])
    assert final < first
    assert (tmp_path / "checkpoint.msgpack").exists()
