"""Planted desync fixture: rank- and data-dependent branches guarding collectives.

Consumed by ``scripts/synclint.py --selftest`` and tests/test_synclint.py.
Expected findings (lines matter -- keep this file stable):

  * line 15 branch on jax.process_index() guards save_checkpoint (line 16)
  * line 18 branch on float(metrics[...]) guards rollback (line 19), which is
    collective-issuing inter-procedurally via psum.
"""


class T:
    def fit(self, state, metrics):
        for i in range(8):
            if jax.process_index() == 0:
                self.save_checkpoint(state, i)
            flag = float(metrics["diverged"])
            if flag > 0.5:
                state = rollback(state)
        return state


def rollback(state):
    return psum(state, "data")
