"""Anchored twin of desync_planted.py: every divergent branch routes through an
agreement point or carries an explicit suppression. Must lint clean."""


class T:
    def fit(self, state, metrics):
        for i in range(8):
            if jax.process_index() == 0:
                self.save_checkpoint(state, i)  # synclint: allow
            flag = self.agree(float(metrics["diverged"]))  # synclint: agreement
            if flag > 0.5:
                state = rollback(state)
        return state


def rollback(state):
    return psum(state, "data")
