"""MoE / expert parallelism: single-expert oracle, sharded experts, recipe."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from pytorch_distributed_tpu.models.moe import MoEMLP, _FFN, moe_specs
from pytorch_distributed_tpu.models.transformer import TransformerLM
from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
from pytorch_distributed_tpu.parallel.tp import shard_pytree


def test_single_expert_equals_dense_ffn():
    """E=1 routes every token to the one expert with gate 1.0 and ample
    capacity, so MoE must equal the plain FFN with the same weights."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 8, 16)).astype(np.float32))
    moe = MoEMLP(n_experts=1, capacity_factor=2.0)
    variables = moe.init(jax.random.PRNGKey(0), x)
    out, _ = moe.apply(variables, x, mutable=["losses"])

    ffn = _FFN(d_model=16, d_hidden=64)
    # vmapped expert params carry a leading E=1 axis; strip it for the oracle.
    ffn_params = jax.tree_util.tree_map(
        lambda a: a[0], variables["params"]["experts"]
    )
    want = ffn.apply({"params": ffn_params}, x.reshape(-1, 16)).reshape(2, 8, 16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


def test_router_records_aux_loss():
    x = jnp.ones((2, 8, 16))
    moe = MoEMLP(n_experts=4)
    variables = moe.init(jax.random.PRNGKey(0), x)
    _, sown = moe.apply({"params": variables["params"]}, x, mutable=["losses"])
    (aux,) = jax.tree_util.tree_leaves(sown["losses"])
    assert float(aux) > 0.0


def test_moe_specs_shard_only_experts():
    model = TransformerLM(vocab_size=32, d_model=16, n_heads=2, n_layers=1,
                          moe_experts=4)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    specs = moe_specs(params)
    flat = jax.tree_util.tree_leaves_with_path(specs)
    expert_specs = [s for p, s in flat
                    if "experts" in [getattr(k, "key", "") for k in p]]
    other_specs = [s for p, s in flat
                   if "experts" not in [getattr(k, "key", "") for k in p]]
    assert expert_specs and all(s[0] == "expert" for s in expert_specs)
    assert all(s == P() for s in other_specs)


def test_expert_params_sharded_on_mesh():
    mesh = build_mesh(MeshSpec(("data", "expert"), (2, 4)), jax.devices()[:8])
    model = TransformerLM(vocab_size=32, d_model=16, n_heads=2, n_layers=1,
                          moe_experts=4)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    sharded = shard_pytree(params, moe_specs(params), mesh)
    fc1 = sharded["block_0"]["moe"]["experts"]["fc1"]["kernel"]
    assert fc1.shape[0] == 4  # E experts stacked
    assert fc1.addressable_shards[0].data.shape[0] == 1  # one expert/device


def test_lm_pretrain_ep_recipe_learns(tmp_path, capsys):
    from pytorch_distributed_tpu.recipes import lm_pretrain

    final = lm_pretrain.main(
        ["--vocab", "32", "--d-model", "32", "--n-heads", "2",
         "--n-layers", "1", "--seq-len", "32", "-b", "8",
         "--steps", "15", "--lr", "0.05", "-p", "4",
         "--dataset-length", "8", "--ep", "4",
         "--precision", "fp32", "--checkpoint-dir", str(tmp_path)]
    )
    out = capsys.readouterr().out
    first = float(out.split("Loss ")[1].split(" ")[0])
    assert final < first


# ------------------------------------------------------------ top-k routing

def test_top2_gates_renormalized_and_finite():
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.models.moe import MoEMLP

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 8, 16)).astype(np.float32))
    m = MoEMLP(n_experts=4, top_k=2)
    v = m.init(jax.random.PRNGKey(0), x)
    y, _ = m.apply(v, x, mutable=["losses"])
    assert y.shape == x.shape and np.isfinite(np.asarray(y)).all()
    # top-2 output differs from top-1 with the same params
    y1, _ = MoEMLP(n_experts=4, top_k=1).apply(v, x, mutable=["losses"])
    assert np.abs(np.asarray(y) - np.asarray(y1)).max() > 1e-6


def test_top2_single_expert_is_dense_ffn():
    """With E=1, top-k clamps to 1 and the layer is exactly the dense FFN
    (gate = softmax over one logit = 1.0)."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.models.moe import MoEMLP, _FFN

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(1, 4, 8)).astype(np.float32))
    m = MoEMLP(n_experts=1, top_k=2, capacity_factor=4.0)
    v = m.init(jax.random.PRNGKey(0), x)
    y, _ = m.apply(v, x, mutable=["losses"])
    ffn = _FFN(d_model=8, d_hidden=32)
    fv = {"params": jax.tree_util.tree_map(
        lambda a: a[0], v["params"]["experts"])}
    want = ffn.apply(fv, x.reshape(4, 8)).reshape(1, 4, 8)
    np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_top2_capacity_never_exceeded():
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.models import moe as moe_mod
    from pytorch_distributed_tpu.models.moe import MoEMLP

    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(2, 16, 8)).astype(np.float32))
    # tiny capacity forces drops; dispatch per expert must stay <= cap
    m = MoEMLP(n_experts=2, top_k=2, capacity_factor=0.25)
    v = m.init(jax.random.PRNGKey(0), x)

    captured = {}
    orig = jnp.einsum

    def spy(spec, *args, **kw):
        if spec == "sec,sd->ecd":
            captured["dispatch"] = args[0]
        return orig(spec, *args, **kw)

    try:
        moe_mod.jnp.einsum = spy
        m.apply(v, x, mutable=["losses"])
    finally:
        moe_mod.jnp.einsum = orig
    d = np.asarray(captured["dispatch"])  # [S, E, cap]
    per_expert = d.sum(axis=(0, 2))
    assert (per_expert <= d.shape[2] + 1e-6).all()
    # each (expert, slot) holds at most one token
    slot_occupancy = d.sum(axis=0)
    assert (slot_occupancy <= 1 + 1e-6).all()


def test_lm_pretrain_moe_top2(tmp_path, capsys):
    from pytorch_distributed_tpu.recipes import lm_pretrain

    final = lm_pretrain.main([
        "--vocab", "32", "--d-model", "32", "--n-heads", "2",
        "--n-layers", "1", "--seq-len", "32", "-b", "8",
        "--steps", "6", "--lr", "0.05", "-p", "2",
        "--dataset-length", "8", "--precision", "fp32",
        "--ep", "2", "--moe-top-k", "2", "--no-eval",
        "--checkpoint-dir", str(tmp_path),
    ])
    assert np.isfinite(final)
