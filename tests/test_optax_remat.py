"""Optax adapter and remat option."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from pytorch_distributed_tpu.models.transformer import TransformerLM
from pytorch_distributed_tpu.train.config import Config
from pytorch_distributed_tpu.train.trainer import Trainer


def test_trainer_with_optax_adamw(tmp_path, capsys):
    cfg = Config(
        arch="resnet18", batch_size=8, epochs=1, print_freq=1, seed=0,
        synthetic=True, synthetic_length=16, image_size=32, num_classes=2,
        checkpoint_dir=str(tmp_path), workers=2,
    )
    t = Trainer(cfg, tx=optax.adamw(1e-3))
    p0 = np.asarray(jax.tree_util.tree_leaves(t.state.params)[0]).copy()
    t.fit()
    out = capsys.readouterr().out
    assert "* Acc@1" in out
    p1 = np.asarray(jax.tree_util.tree_leaves(t.state.params)[0])
    assert not np.array_equal(p0, p1)
    # adamw opt_state round-trips through the msgpack checkpoint
    from pytorch_distributed_tpu.train.checkpoint import load_checkpoint

    restored, _ = load_checkpoint(str(tmp_path / "checkpoint.msgpack"), t.state)
    for a, b in zip(jax.tree_util.tree_leaves(restored.momentum),
                    jax.tree_util.tree_leaves(t.state.momentum)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_remat_model_matches_no_remat():
    kw = dict(vocab_size=32, d_model=32, n_heads=2, n_layers=2)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 32, size=(2, 16)).astype(np.int32)
    )
    plain = TransformerLM(**kw)
    remat = TransformerLM(**kw, remat=True)
    params = plain.init(jax.random.PRNGKey(0), tokens)

    out_p = plain.apply(params, tokens)
    out_r = remat.apply(params, tokens)
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_r),
                               rtol=1e-6, atol=1e-6)

    def loss(m, p):
        return jnp.sum(m.apply(p, tokens) ** 2)

    gp = jax.grad(lambda p: loss(plain, p))(params)
    gr = jax.grad(lambda p: loss(remat, p))(params)
    # rematerialized backward recomputes the forward inside the cotangent
    # program, and XLA may fuse/reassociate the recompute differently from
    # the stashed-activation path — observed up to ~2e-4 relative on this
    # backend; the comparison is correctness of the remat graph, not
    # bitwise scheduling.
    for a, b in zip(jax.tree_util.tree_leaves(gp), jax.tree_util.tree_leaves(gr)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)
