"""Orbax backend: async sharded save / restore round-trip (+Trainer flag)."""

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_tpu.train.checkpoint import (
    load_checkpoint,
    save_checkpoint,
)
from pytorch_distributed_tpu.train.state import TrainState


def _state(seed=0):
    rng = np.random.default_rng(seed)
    params = {
        "fc": {"kernel": jnp.asarray(rng.normal(size=(8, 4)).astype(np.float32)),
               "bias": jnp.zeros((4,), jnp.float32)},
    }
    mom = jax.tree_util.tree_map(jnp.zeros_like, params)
    return TrainState(step=jnp.int32(7), params=params, batch_stats={},
                      momentum=mom)


def test_orbax_round_trip(tmp_path):
    state = _state()
    path = save_checkpoint(str(tmp_path), state, epoch=3, arch="resnet18",
                           best_acc1=42.5, is_best=True, backend="orbax")
    assert path is not None
    template = _state(seed=99)  # different values, same structure
    restored, meta = load_checkpoint(str(tmp_path), template)
    assert meta["epoch"] == 3 and meta["arch"] == "resnet18"
    assert meta["best_acc1"] == 42.5
    assert int(restored.step) == 7
    for a, b in zip(jax.tree_util.tree_leaves(state.params),
                    jax.tree_util.tree_leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_orbax_keeps_latest_epoch(tmp_path):
    s1, s2 = _state(1), _state(2)
    save_checkpoint(str(tmp_path), s1, 0, "resnet18", 10.0, False,
                    backend="orbax")
    save_checkpoint(str(tmp_path), s2, 1, "resnet18", 20.0, False,
                    backend="orbax")
    restored, meta = load_checkpoint(str(tmp_path), _state(99))
    assert meta["epoch"] == 1
    for a, b in zip(jax.tree_util.tree_leaves(s2.params),
                    jax.tree_util.tree_leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_trainer_orbax_flag(tmp_path):
    from pytorch_distributed_tpu.train.config import Config
    from pytorch_distributed_tpu.train.trainer import Trainer

    cfg = Config(
        arch="resnet18", batch_size=8, epochs=1, print_freq=1, seed=0,
        synthetic=True, synthetic_length=16, image_size=32, num_classes=2,
        checkpoint_dir=str(tmp_path), workers=2, ckpt_backend="orbax",
    )
    Trainer(cfg).fit()
    assert (tmp_path / "orbax").is_dir()
    # resume from the orbax directory via autodetect
    cfg2 = Config(
        arch="resnet18", batch_size=8, epochs=1, print_freq=1, seed=0,
        synthetic=True, synthetic_length=16, image_size=32, num_classes=2,
        checkpoint_dir=str(tmp_path), workers=2,
        resume=str(tmp_path),
    )
    t2 = Trainer(cfg2)
    assert t2.cfg.start_epoch == 1
