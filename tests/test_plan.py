"""The autoplan subsystem (pytorch_distributed_tpu/plan/).

Three layers of coverage, mirroring the package's layering contract:

- pure planning (space/cost/planner): enumeration exclusions, feasibility
  pruning with itemized reasons, score monotonicity in chip count, and
  rank stability against the checked-in expectation table
  (tests/data/autoplan_expect.json) — no mesh, no compiles;
- the lowering service (analysis/lowering.py): artifact persist/load
  round-trip, the jaxlib persistent-cache version guard + memoized
  self-check, and the tier-1 compile-budget fence — shardlint detectors,
  both ledger sweeps, and autoplan's top-k validation must all ride ONE
  shared AOT sweep with zero extra compiles;
- top-k validation parity on the simulated 4-way mesh: the planner's
  analytic predictions for the tiny-LM winner must agree with the real
  compiled ledgers within the existing acceptance fences (±15% comm
  payload, ±15% peak HBM, ±10% ledger-vs-measured).
"""

import json
import os

import pytest

from pytorch_distributed_tpu.plan import cost as cost_mod
from pytorch_distributed_tpu.plan import planner, space
from pytorch_distributed_tpu.plan.space import (
    ModelSpec,
    Plan,
    elastic_worlds,
    enumerate_plans,
    lm_spec,
    resnet50_spec,
    tiny_lm_spec,
)

EXPECT_PATH = os.path.join(os.path.dirname(__file__), "data",
                           "autoplan_expect.json")


def _lm(**overrides) -> ModelSpec:
    base = dict(name="lm-test", family="lm", batch=8, vocab=64, d_model=32,
                n_layers=2, n_heads=4, seq=16)
    base.update(overrides)
    return ModelSpec(**base)


# --------------------------------------------------------- enumeration

def test_enumerate_image_is_dp_times_knobs():
    plans = enumerate_plans(resnet50_spec(), 8)
    assert len(plans) == 2 * 4  # zero x grad_compress
    assert all(p.dp == 8 and p.tp == 1 and p.pp == 1 for p in plans)
    assert {p.grad_compress for p in plans} == {"none", "bf16", "int8",
                                                "fp8"}


def test_enumerate_lm_structural_exclusions():
    plans = enumerate_plans(_lm(), 8)
    assert plans
    for p in plans:
        # Megatron TP always pairs with the vocab-sharded fused head.
        assert not (p.tp > 1 and p.fused_ce_mode != "tp"), p.key()
        assert not (p.tp == 1 and p.fused_ce_mode == "tp"), p.key()
        # ZeRO-3 already shards what WUS would.
        assert not (p.fsdp and p.zero == "wus"), p.key()
        assert p.dp * p.tp * p.pp == 8, p.key()


def test_factorizations_cover_the_lattice():
    facts = set(space._factorizations(8, 3))
    assert all(a * b * c == 8 for a, b, c in facts)
    assert (8, 1, 1) in facts and (2, 2, 2) in facts and (1, 1, 8) in facts
    assert len(facts) == 10


def test_microbatches_gpipe_rule():
    # per-dp batch 8, 2 stages: largest divisor in [2, 8] is 8.
    assert Plan(spec=_lm(batch=16), chips=4, dp=2, pp=2).microbatches == 8
    # prime per-dp shard with no divisor >= pp: infeasible marker 0.
    assert Plan(spec=_lm(batch=17), chips=2, dp=1,
                pp=2).microbatches == 0
    assert Plan(spec=_lm(), chips=4, dp=4).microbatches == 1


# --------------------------------------------------------- feasibility

def _hw():
    return cost_mod.hw_for("v5p")


def _reasons(plan, hbm_budget=None):
    return cost_mod.feasibility(plan, _hw(), hbm_budget=hbm_budget)


def test_feasibility_mesh_product_mismatch():
    rs = _reasons(Plan(spec=_lm(), chips=8, dp=2, tp=1, pp=1))
    assert rs and any("8" in r for r in rs)


def test_feasibility_indivisible_vocab_and_heads():
    rs = _reasons(Plan(spec=_lm(vocab=65), chips=4, dp=2, tp=2,
                       fused_ce_mode="tp"))
    assert any("vocab" in r for r in rs), rs
    rs = _reasons(Plan(spec=_lm(n_heads=3), chips=4, dp=2, tp=2,
                       fused_ce_mode="tp"))
    assert any("head" in r for r in rs), rs


def test_feasibility_indivisible_stages_and_microbatch():
    rs = _reasons(Plan(spec=_lm(n_layers=5), chips=4, dp=2, pp=2))
    assert any("stage" in r for r in rs), rs
    rs = _reasons(Plan(spec=_lm(batch=17), chips=2, dp=1, pp=2))
    assert any("microbatch" in r for r in rs), rs


def test_feasibility_hbm_budget_prunes_everything():
    plan = Plan(spec=_lm(), chips=4, dp=4)
    assert not _reasons(plan)
    rs = _reasons(plan, hbm_budget=1.0)
    assert any("exceeds" in r and "HBM" in r for r in rs), rs


def test_pruned_histogram_buckets_by_reason_class():
    ranked, pruned = planner.rank_plans(tiny_lm_spec(), 4, _hw(),
                                        hbm_budget=1.0)
    assert not ranked
    assert "peak HBM over budget" in pruned, pruned
    # buckets are reason classes, never per-value strings
    assert not any("GB" in k for k in pruned), pruned


# ------------------------------------------------------------- scoring

def test_score_monotonic_in_chip_count():
    """Doubling the pod never slows the predicted step: the fastest plan's
    step time is non-increasing in chips for both families on v5p."""
    for spec in (lm_spec(), resnet50_spec()):
        prev = None
        for chips in (4, 8, 16, 32):
            ranked, _ = planner.rank_plans(spec, chips, _hw())
            assert ranked, f"{spec.name}@{chips} has no feasible plan"
            score = ranked[0][1]
            assert 0.0 < score.mfu_pct <= 100.0
            assert score.step_time_s > 0
            if prev is not None:
                assert score.step_time_s <= prev, (
                    f"{spec.name}: step time rose from {prev} at "
                    f"{chips // 2} chips to {score.step_time_s} at {chips}")
            prev = score.step_time_s


def test_score_fields_are_consistent():
    plan = Plan(spec=lm_spec(), chips=8, dp=8, remat=True)
    score = cost_mod.score_plan(plan, _hw())
    d = score.to_dict()
    assert d["step_time_ms"] == pytest.approx(score.step_time_s * 1e3)
    assert d["wire_bytes"] > 0 and d["payload_bytes"] > 0
    assert d["peak_hbm_bytes"] > 0
    assert score.step_time_s >= score.compute_s


def test_rank_tiebreak_prefers_fewer_knobs():
    # At tiny shapes ZeRO-1 WUS ties plain DP on predicted wire bytes by
    # construction; the complexity tie-break must keep the fully-fenced
    # plain-DP recipe on top.
    ranked, _ = planner.rank_plans(tiny_lm_spec(), 4, cost_mod.hw_for(None))
    assert ranked[0][0].key() == "c4/dp4"
    assert cost_mod.plan_complexity(ranked[0][0]) == 0


# ------------------------------------------------ measured overlap fold

def test_overlap_from_timeline_folds_into_scores(tmp_path):
    """ISSUE 13 S2: ``--overlap-from`` replaces the assumed backward-
    overlap fraction with the profiler's measured overlap_pct_mean, and
    the fold is visible in the score — a lower measured overlap exposes
    more comm, so no plan's predicted step gets faster."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts"))
    import autoplan as autoplan_cli

    report = {"captures": [
        {"file": "a.xplane.pb",
         "aggregate": {"steps": 2, "overlap_pct_mean": 30.0}},
        {"file": "b.xplane.pb",
         "aggregate": {"steps": 2, "overlap_pct_mean": 50.0}},
        {"file": "idle.xplane.pb", "aggregate": {"steps": 0}},  # skipped
    ]}
    path = tmp_path / "timeline.json"
    path.write_text(json.dumps(report))
    frac = autoplan_cli.overlap_from_timeline(str(path))
    assert frac == pytest.approx(0.40)  # mean of the step-bearing captures

    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"captures": []}))
    with pytest.raises(ValueError):
        autoplan_cli.overlap_from_timeline(str(empty))

    assumed = planner.autoplan("lm", 32, chip="v5p", top_k=3,
                               elastic=False)
    measured = planner.autoplan("lm", 32, chip="v5p", top_k=3,
                                elastic=False, overlap=frac)
    assert assumed["overlap_source"] == "assumed"
    assert assumed["overlap"] == cost_mod.DEFAULT_OVERLAP
    assert measured["overlap_source"] == "measured"
    assert measured["overlap"] == pytest.approx(frac)
    by_key = {e["plan"]["key"]: e["predicted"]["step_time_ms"]
              for e in assumed["ranked"]}
    for e in measured["ranked"]:
        if e["plan"]["key"] in by_key:
            assert (e["predicted"]["step_time_ms"]
                    >= by_key[e["plan"]["key"]] - 1e-9)

    # CLI end to end: measured overlap flows through the sweep
    assert autoplan_cli.main(["lm-tiny", "--chips", "4", "--no-elastic",
                              "--overlap-from", str(path)]) == 0


# --------------------------------------------- schedule-derived overlap

def test_bucketed_overlap_schedule_math():
    """ISSUE 16: the bucketed scheduler's hideable fraction is (K-1)/K —
    every reverse-autodiff bucket's collective except the last overlaps
    remaining backward — capped below 1.0 (tail bucket + dispatch are
    never free)."""
    mib = 1024.0 * 1024.0
    assert cost_mod.bucketed_overlap(3.5 * mib, bucket_mb=4.0) == 0.0
    assert cost_mod.bucketed_overlap(16 * mib, bucket_mb=4.0) \
        == pytest.approx(3 / 4)
    assert cost_mod.bucketed_overlap(17 * mib, bucket_mb=4.0) \
        == pytest.approx(4 / 5)  # ceil: a partial tail bucket counts
    assert cost_mod.bucketed_overlap(4096 * mib, bucket_mb=4.0) == 0.95
    with pytest.raises(ValueError):
        cost_mod.bucketed_overlap(16 * mib, bucket_mb=0.0)

    # spec wrapper: full f32 gradient bytes of the spec's param count
    spec = tiny_lm_spec()
    params = cost_mod.step_cost_for(space.Plan(spec=spec, chips=1)).params
    assert cost_mod.spec_bucketed_overlap(spec, bucket_mb=4.0) \
        == cost_mod.bucketed_overlap(4.0 * params, bucket_mb=4.0)


def test_autoplan_overlap_source_schedule(tmp_path):
    """``overlap_source="schedule"`` flows through the payload (planner
    kwarg and the ``--overlap-schedule`` CLI), distinct from the
    measured-timeline provenance."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts"))
    import autoplan as autoplan_cli

    frac = cost_mod.spec_bucketed_overlap(lm_spec(), bucket_mb=4.0)
    payload = planner.autoplan("lm", 32, chip="v5p", top_k=3,
                               elastic=False, overlap=frac,
                               overlap_source="schedule")
    assert payload["overlap_source"] == "schedule"
    assert payload["overlap"] == pytest.approx(frac)
    # the explicit kwarg never mislabels the default provenance
    assert planner.autoplan("lm", 32, chip="v5p", top_k=3,
                            elastic=False)["overlap_source"] == "assumed"

    # CLI end to end, and exclusive with --overlap-from
    assert autoplan_cli.main(["lm-tiny", "--chips", "4", "--no-elastic",
                              "--overlap-schedule"]) == 0
    report = tmp_path / "timeline.json"
    report.write_text(json.dumps({"captures": [
        {"file": "a", "aggregate": {"steps": 1,
                                    "overlap_pct_mean": 50.0}}]}))
    with pytest.raises(SystemExit):
        autoplan_cli.main(["lm-tiny", "--chips", "4", "--no-elastic",
                           "--overlap-schedule", "--overlap-from",
                           str(report)])


# ------------------------------------------------- rank stability table

def test_rank_stability_against_checked_in_table():
    """The planner's ranking is a pure function of the checked-in cost
    tables; any drift (a flops-table edit, a new exclusion) must show up
    as a reviewed diff of tests/data/autoplan_expect.json, not silently."""
    with open(EXPECT_PATH) as f:
        expect = json.load(f)

    def keys(payload):
        return [e["plan"]["key"] for e in payload["ranked"]]

    p = planner.autoplan("lm-tiny", 4, top_k=5)
    want = expect["lm-tiny@4"]
    assert keys(p) == want["top"]
    assert p["feasible"] == want["feasible"]
    assert p["enumerated"] == want["enumerated"]
    got_elastic = {w: (e["plan"]["key"] if e else None)
                   for w, e in p["elastic"].items()}
    assert got_elastic == want["elastic"]

    p = planner.autoplan("lm", 8, chip="v5p", top_k=5)
    want = expect["lm@8:v5p"]
    assert keys(p) == want["top"]
    assert p["feasible"] == want["feasible"]
    assert p["ranked"][0]["predicted"]["mfu_pct"] == pytest.approx(
        want["top_mfu_pct"], abs=0.01)

    p = planner.autoplan("resnet50", 32, chip="v5p", top_k=5)
    want = expect["resnet50@32:v5p"]
    assert keys(p) == want["top"]
    assert p["feasible"] == want["feasible"]


# ------------------------------------------------------ flags / payload

def test_lm_flags_match_recipe_spellings():
    plan = Plan(spec=_lm(batch=16), chips=8, dp=2, tp=2, pp=2, fsdp=True,
                remat=True, fused_ce_mode="tp")
    flags = plan.flags()
    for needle in ("--vocab", "--d-model", "--n-layers", "--n-heads",
                   "--seq-len", "--batch-size", "--tp", "--pp",
                   "--microbatches", "--fsdp", "--remat", "--fused-ce",
                   "--fused-ce-mode"):
        assert needle in flags, (needle, flags)
    assert flags[flags.index("--tp") + 1] == "2"
    assert flags[flags.index("--fused-ce-mode") + 1] == "tp"
    assert plan.cli().startswith(
        "python -m pytorch_distributed_tpu.recipes.lm_pretrain ")


def test_image_flags_match_config_spellings():
    plan = Plan(spec=resnet50_spec(), chips=4, dp=4, zero="wus",
                grad_compress="int8")
    flags = plan.flags()
    assert flags[:2] == ["-a", "resnet50"]
    assert flags[flags.index("--zero") + 1] == "wus"
    assert flags[flags.index("--grad-compress") + 1] == "int8"
    assert "--batch-size" in flags
    assert plan.cli().startswith("python main.py ")


def test_elastic_worlds_and_payload():
    assert elastic_worlds(32) == [32, 31, 16]
    assert elastic_worlds(2) == [2, 1]
    payload = planner.autoplan("resnet50", 8, chip="v5p", top_k=2)
    assert payload["schema_version"] == planner.PLAN_SCHEMA_VERSION
    assert set(payload["elastic"]) == {"7", "4"}
    for entry in payload["ranked"]:
        assert entry["predicted"]["mfu_pct"] > 0
        assert "--batch-size" in entry["plan"]["cli"]
    assert "validation" not in payload  # jax-free unless asked


def test_predicted_mfu_and_best_plan():
    mfu = planner.predicted_mfu("resnet50", 4, chip="v5p")
    assert mfu is not None and 0.0 < mfu <= 100.0
    best = planner.best_plan("lm-tiny", 4)
    assert best is not None and best.chips == 4 and best.key() == "c4/dp4"


# ------------------------------------------- persistent-cache guard

def test_jaxlib_version_guard():
    from pytorch_distributed_tpu.analysis import lowering

    assert lowering.jaxlib_version_tuple("0.4.36") == (0, 4, 36)
    assert lowering.jaxlib_version_tuple("0.5.0") == (0, 5, 0)
    assert lowering.persistent_cache_known_bad("0.4.36")
    assert lowering.persistent_cache_known_bad("0.4.37")
    assert not lowering.persistent_cache_known_bad("0.5.0")
    assert not lowering.persistent_cache_known_bad("0.6.2")


def test_maybe_enable_short_circuits_on_known_bad(monkeypatch):
    from pytorch_distributed_tpu.analysis import lowering

    if not lowering.persistent_cache_known_bad():
        pytest.skip("jaxlib here is outside the known-bad range")
    monkeypatch.delenv("PTD_PERSISTENT_CACHE", raising=False)
    verdict = lowering.maybe_enable_persistent_cache()
    assert verdict["enabled"] is False
    assert "known-bad" in verdict["reason"]


def test_maybe_enable_force_disable(monkeypatch):
    from pytorch_distributed_tpu.analysis import lowering

    monkeypatch.setenv("PTD_PERSISTENT_CACHE", "0")
    verdict = lowering.maybe_enable_persistent_cache()
    assert verdict["enabled"] is False and "PTD_PERSISTENT_CACHE=0" in (
        verdict["reason"])


def test_gate_verdict_logged_once_per_session(monkeypatch, capsys):
    from pytorch_distributed_tpu.analysis import lowering

    monkeypatch.setenv("PTD_PERSISTENT_CACHE", "0")
    monkeypatch.setattr(lowering, "_GATE_VERDICT_LOGGED", False)
    lowering.maybe_enable_persistent_cache()
    err = capsys.readouterr().err
    ver = ".".join(map(str, lowering.jaxlib_version_tuple()))
    assert "[lowering] persistent compilation cache disabled" in err
    assert f"jaxlib {ver}" in err
    assert "PTD_PERSISTENT_CACHE=0" in err
    # second call in the same session: the verdict line must not repeat
    lowering.maybe_enable_persistent_cache()
    assert capsys.readouterr().err == ""


class _FakeRun:
    def __init__(self, returncode, stdout):
        self.returncode = returncode
        self.stdout = stdout


def test_selfcheck_roundtrip_and_memo(tmp_path):
    from pytorch_distributed_tpu.analysis import lowering

    cache = str(tmp_path / "jaxcache")
    calls = []

    def good_runner():
        calls.append(1)
        return _FakeRun(0, "129.0\n")

    assert lowering.persistent_cache_selfcheck(cache, _runner=good_runner)
    assert len(calls) == 2  # populate + warm
    assert os.path.exists(os.path.join(cache, "selfcheck.json"))

    def must_not_run():
        raise AssertionError("self-check verdict must be memoized")

    assert lowering.persistent_cache_selfcheck(cache, _runner=must_not_run)


def test_selfcheck_fails_on_crash_and_mismatch(tmp_path):
    from pytorch_distributed_tpu.analysis import lowering

    crash = str(tmp_path / "crash")
    assert not lowering.persistent_cache_selfcheck(
        crash, _runner=lambda: _FakeRun(134, ""))
    outs = iter(["1.0\n", "2.0\n"])
    drift = str(tmp_path / "drift")
    assert not lowering.persistent_cache_selfcheck(
        drift, _runner=lambda: _FakeRun(0, next(outs)))


# ------------------------------------------- shared sweep + validation

def test_compile_budget_arithmetic():
    from pytorch_distributed_tpu.analysis import core, lowering

    assert lowering.compile_budget() == (
        len(core.RECIPES) + lowering.EXTRA_COMPILE_ALLOWANCE)


def test_service_persist_load_roundtrip(get_lowering):
    """Disk artifacts reproduce the live ledgers exactly: a subprocess
    reading <name>.hlo/<name>.json gets the same comm/memory truth as the
    in-process sweep, with no recompile."""
    from pytorch_distributed_tpu.analysis import core

    low = get_lowering("lm_train_dp")
    svc = get_lowering.service
    assert svc.has("lm_train_dp")
    assert "lm_train_dp" in svc.names()
    cached = svc.load("lm_train_dp")
    assert cached.mesh_shape == dict(low.mesh_shape)
    assert cached.measured_peak_bytes > 0
    live_comm = core.comm_ledger_for("lm_train_dp")
    disk_comm = cached.comm_ledger()
    assert disk_comm.total_bytes == live_comm.total_bytes
    assert disk_comm.total_wire_bytes == live_comm.total_wire_bytes
    live_mem = core.mem_ledger_for("lm_train_dp")
    disk_mem = cached.mem_ledger()
    assert disk_mem.peak_bytes == live_mem.peak_bytes
    assert "params" in cached.arg_classes


def test_validate_top_k_parity_on_cpu_mesh(get_lowering):
    """The acceptance fence: the tiny-LM winner's analytic predictions
    agree with its lowered step's ledgers within the existing thresholds
    (±15% comm payload, ±15% peak HBM, ±10% ledger-vs-measured)."""
    from pytorch_distributed_tpu.plan import validate as validate_mod

    ranked, _ = planner.rank_plans(tiny_lm_spec(), 4, cost_mod.hw_for(None))
    recs = validate_mod.validate_top_k([p for p, _ in ranked], k=3,
                                       service=get_lowering.service)
    assert len(recs) == 3
    top = recs[0]
    assert top["plan"] == "c4/dp4" and top["recipe"] == "lm_train_dp"
    assert top["ok"] is True
    comm = top["checks"]["comm"]
    assert comm["fenced"] and comm["ok"]
    assert comm["residual_pct"] <= validate_mod.COMM_FENCE_PCT
    mem = top["checks"]["mem"]
    assert mem["fenced"] and mem["ok"]
    assert mem["residual_pct"] <= validate_mod.MEM_FENCE_PCT
    led = top["checks"]["ledger_vs_measured"]
    assert led["ok"] and led["residual_pct"] <= validate_mod.LEDGER_FENCE_PCT
    # every validated record either passed its fences or was analytic-only
    assert all(r["ok"] is not False for r in recs)


def test_one_sweep_feeds_every_static_consumer(get_lowering):
    """The tier-1 compile-budget fence (the tentpole's zero-extra-compiles
    contract): with the recipe sweep warm, the shardlint detector pass,
    both ledger sweeps, AND autoplan's validated top-k must add ZERO
    compiles — and the process-wide total must sit under the budget."""
    from pytorch_distributed_tpu.analysis import core, lowering

    for name in core.RECIPES:
        get_lowering(name)
    before = get_lowering.compile_count()

    reports = core.analyze_all()
    assert len(reports) >= len(core.RECIPES)
    comm_ledgers = core.sweep_comm_ledgers()
    mem_ledgers = core.sweep_mem_ledgers()
    assert comm_ledgers and mem_ledgers
    payload = planner.autoplan("lm-tiny", 4, validate=True, validate_k=3)
    assert payload["validation_ok"] is True
    assert len(payload["validation"]) == 3

    grew = get_lowering.compile_count() - before
    assert grew == 0, (
        f"static consumers paid {grew} extra compile(s); they must all "
        f"ride the shared lowering sweep")
    assert get_lowering.compile_count() <= get_lowering.compile_budget()
    lowering.assert_compile_budget()
