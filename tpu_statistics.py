#!/usr/bin/env python
"""TPU telemetry to CSV — reference statistics.sh parity (statistics.sh:1-4).

Usage:  python tpu_statistics.py [outfile.csv] [interval_seconds]
Samples per-device memory stats every 500 ms (default) until Ctrl-C.
"""

import sys
import time

from pytorch_distributed_tpu.utils.telemetry import TelemetrySampler


def main() -> None:
    path = sys.argv[1] if len(sys.argv) > 1 else "tpu_statistics.csv"
    interval = float(sys.argv[2]) if len(sys.argv) > 2 else 0.5
    sampler = TelemetrySampler(path, interval).start()
    print(f"sampling device memory to {path} every {interval}s (Ctrl-C to stop)")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        sampler.stop()


if __name__ == "__main__":
    main()
